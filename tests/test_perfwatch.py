"""The performance sentinel (core/sentinel.py) and the perf regression
gate (utils/perfwatch.py): gate arithmetic pinned against the checked-in
BENCH_r*.json history, watchdog anomaly semantics (fire-once, cooldown,
attribution), flight-dump retention, the /metrics + /healthz endpoint,
and the unified stats --json envelope."""

import contextlib
import io
import json
import os
import re
import time

import numpy as np
import pytest

from horovod_tpu.core import sentinel as sen
from horovod_tpu.core import telemetry as tele
from horovod_tpu.utils import perfwatch as pw

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def fresh_sentinel(monkeypatch):
    """A sentinel rebuilt from THIS test's env (the suite default is
    HVD_WATCHDOG=0, see conftest) and torn down after, so one test's
    watchdog state never leaks into the next."""

    def make(**env):
        for k, v in env.items():
            if v is None:
                monkeypatch.delenv(k, raising=False)
            else:
                monkeypatch.setenv(k, str(v))
        sen.reset_sentinel()
        return sen.get_sentinel()

    yield make
    sen.reset_sentinel()
    tele.STRAGGLERS.reset()


# ---------------------------------------------------------------------------
# perfwatch: loading + gate arithmetic over the checked-in history
# ---------------------------------------------------------------------------

def test_perfwatch_is_stdlib_only():
    """bench.py --check depends on this module staying import-light (the
    --dry guard proves argparse paths never pay jax; the gate itself
    must stay runnable on a CI box with no framework)."""
    src = open(os.path.join(REPO, "horovod_tpu", "utils",
                            "perfwatch.py")).read()
    assert not re.search(r"^\s*(import|from)\s+(jax|numpy|tensorflow|"
                         r"torch|horovod_tpu)\b", src, re.M), \
        "perfwatch.py must stay stdlib-only"


def test_load_history_fixtures():
    hist = pw.load_history(REPO)
    labels = [r["label"] for r in hist]
    assert labels[:5] == ["r01", "r02", "r03", "r04", "r05"]
    r05 = hist[labels.index("r05")]
    assert r05["value"] == 2938.4
    assert r05["hbm_gb_per_step"] == 7.81
    # The recorded iteration spread (2919-2951 over median 2938.4).
    assert r05["spread_frac"] == pytest.approx((2951 - 2919) / 2938.4)
    # BASELINE.json is metadata-only today: no numeric record.
    assert pw.load_record(os.path.join(REPO, "BASELINE.json")) is None


def test_gate_passes_on_r05_against_history():
    hist = pw.load_history(REPO)
    cur = pw.load_record(os.path.join(REPO, "BENCH_r05.json"))
    ref = pw.pick_reference(hist, cur)
    assert ref["label"] == "r05"  # newest same-metric record
    result = pw.gate(cur, ref)
    assert result["status"] == "pass", result
    fields = {c["field"] for c in result["checks"]}
    assert fields == {"value", "hbm_gb_per_step"}
    # And an honest improvement (r05 vs r04) passes too.
    r04 = next(r for r in hist if r["label"] == "r04")
    assert pw.gate(cur, r04)["status"] == "pass"


def test_gate_fails_on_doctored_img_per_sec_drop():
    hist = pw.load_history(REPO)
    cur = pw.load_record(os.path.join(REPO, "BENCH_r05.json"))
    cur["value"] = round(cur["value"] * 0.90, 2)  # -10%
    result = pw.gate(cur, pw.pick_reference(hist, cur))
    assert result["status"] == "fail"
    bad = [c for c in result["checks"] if not c["ok"]]
    assert [c["field"] for c in bad] == ["value"]
    # The bound is noise-aware: spread (~1.1%) below the 2% floor, so
    # the floor rules -> reference * (1 - 0.02 * 1.5).
    assert bad[0]["bound"] == pytest.approx(
        2938.4 * (1 - pw.MIN_NOISE * pw.NOISE_MULT), abs=0.01)


def test_gate_fails_on_hbm_traffic_creep():
    hist = pw.load_history(REPO)
    cur = pw.load_record(os.path.join(REPO, "BENCH_r05.json"))
    cur["hbm_gb_per_step"] = round(cur["hbm_gb_per_step"] * 1.10, 3)
    result = pw.gate(cur, pw.pick_reference(hist, cur))
    assert result["status"] == "fail"
    bad = [c for c in result["checks"] if not c["ok"]]
    assert [c["field"] for c in bad] == ["hbm_gb_per_step"]
    assert bad[0]["bound"] == pytest.approx(7.81 * (1 + pw.HBM_TOL),
                                            abs=1e-3)


def test_gate_skips_cleanly():
    # No history at all.
    assert pw.gate({"value": 1.0}, None)["status"] == "skip"
    # Metric mismatch: a vgg run must not gate against the resnet line.
    hist = pw.load_history(REPO)
    other = {"metric": "vgg16_train_images_per_sec_per_chip_bs32",
             "value": 100.0}
    assert pw.pick_reference(hist, other) is None
    # Null fields skip their check, not the whole gate: a CPU record
    # with no measured HBM still gates on throughput.
    cur = pw.load_record(os.path.join(REPO, "BENCH_r05.json"))
    cur["hbm_gb_per_step"] = None
    result = pw.gate(cur, pw.pick_reference(hist, cur))
    assert result["status"] == "pass"
    assert [c["field"] for c in result["checks"]] == ["value"]


def test_perfwatch_cli_trend_and_check(tmp_path, capsys):
    # Trend table over the checked-in history.
    assert pw.main(["--history", REPO]) == 0
    out = capsys.readouterr().out
    assert "r05" in out and "2938" in out
    # The byte-diet delta column (HBM diet round 2): hbm_gb_per_step
    # movement is visible next to the headline Δ%.
    assert "hbmΔ%" in out


def test_trend_table_hbm_delta_column():
    """The hbm delta tracks the previous non-null hbm record — a byte
    cut shows negative, a creep positive, nulls pass through as '-'."""
    recs = [
        {"label": "r1", "value": 2900.0, "hbm_gb_per_step": 7.8},
        {"label": "r2", "value": 2920.0, "hbm_gb_per_step": None},
        {"label": "r3", "value": 2950.0, "hbm_gb_per_step": 5.85},
    ]
    table = pw.trend_table(recs)
    rows = table.splitlines()
    assert "hbmΔ%" in rows[0]
    r2 = next(r for r in rows if r.startswith("r2"))
    assert r2.rstrip().endswith("-")
    r3 = next(r for r in rows if r.startswith("r3"))
    # 5.85 vs 7.8 = -25.0%
    assert "-25.0" in r3


def test_perfwatch_cli_gate(tmp_path, capsys):
    # A passing record file gates green...
    good = tmp_path / "good.json"
    good.write_text(json.dumps(
        {"metric": "resnet50_train_images_per_sec_per_chip_bs32",
         "value": 2940.0, "hbm_gb_per_step": 7.8, "spread_pct": 1.1}))
    assert pw.main([str(good), "--history", REPO, "--check"]) == 0
    # ...a doctored one exits 2 with the failing field named.
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(
        {"metric": "resnet50_train_images_per_sec_per_chip_bs32",
         "value": 2644.0, "hbm_gb_per_step": 8.6}))
    capsys.readouterr()
    assert pw.main([str(bad), "--history", REPO, "--check"]) == 2
    out = capsys.readouterr().out
    assert "FAIL" in out and "hbm_gb_per_step" in out
    # perf.jsonl loads line-per-record; the last record gates.
    pj = tmp_path / "perf.jsonl"
    pj.write_text(
        json.dumps({"kind": "periodic", "hbm_gb_per_step": 7.5}) + "\n" +
        json.dumps({"kind": "periodic", "hbm_gb_per_step": 9.9}) + "\n")
    recs = pw.load_records(str(pj))
    assert len(recs) == 2
    assert pw.load_record(str(pj))["hbm_gb_per_step"] == 9.9
    # Unnamed capture records gate against the log's EARLIER captures —
    # never against the named bench history (pick_reference refuses the
    # cross): 9.9 GB vs the log's own 7.5 GB is a creep -> exit 2.
    assert pw.pick_reference(pw.load_history(REPO), recs[-1]) is None
    assert pw.main([str(pj), "--history", REPO, "--check"]) == 2


# ---------------------------------------------------------------------------
# Watchdog semantics
# ---------------------------------------------------------------------------

def test_watchdog_warmup_fire_once_and_cooldown(fresh_sentinel, tmp_path,
                                                monkeypatch):
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    s = fresh_sentinel(HVD_WATCHDOG=1, HVD_WATCHDOG_MIN_STEPS=8,
                       HVD_WATCHDOG_COOLDOWN=5, HVD_PROFILE_DIR=None)
    # Warmup: nothing fires below min_steps, whatever the excursion.
    for _ in range(7):
        assert s.observe_step(0.010, origin="t") is None
    # Steady baseline, then one 20x step.
    for _ in range(10):
        assert s.observe_step(0.010, origin="t") is None
    v = s.observe_step(0.200, origin="t")
    assert v is not None and v["origin"] == "t"
    assert v["step_s"] == pytest.approx(0.2)
    assert v["threshold_s"] < 0.2
    assert v["verdict"] == "unattributed"
    assert v["dump"] and os.path.exists(v["dump"])
    dump = json.load(open(v["dump"]))
    assert dump["reason"].startswith("watchdog:")
    assert any(ev["name"] == "WATCHDOG_VERDICT" for ev in dump["events"])
    # Cooldown: repeated excursions are suppressed, not re-fired.
    for _ in range(5):
        assert s.observe_step(0.200, origin="t") is None
    wd = s.watchdog("t")
    assert wd.anomalies == 1 and wd.suppressed >= 1
    dumps = [f for f in os.listdir(tmp_path)
             if f.startswith("hvd_flight")]
    assert len(dumps) == 1, dumps
    # Health reflects the verdict.
    h = s.health()
    assert h["status"] == "warn"
    assert h["verdict"]["verdict"] == "unattributed"
    assert h["watchdogs"]["t"]["anomalies"] == 1


def test_watchdog_recompile_attribution(fresh_sentinel, tmp_path,
                                        monkeypatch):
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    s = fresh_sentinel(HVD_WATCHDOG=1, HVD_WATCHDOG_MIN_STEPS=4,
                       HVD_PROFILE_DIR=None)
    for _ in range(10):
        s.observe_step(0.010, origin="d")
    # A compile event lands DURING the anomalous step.
    with sen._compile_lock:
        sen._compile_count += 1
    v = s.observe_step(0.300, origin="d")
    assert v is not None and v["verdict"] == "recompile"
    assert v["compiles"] == 1


def test_watchdog_straggler_attribution(fresh_sentinel, tmp_path,
                                        monkeypatch):
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    s = fresh_sentinel(HVD_WATCHDOG=1, HVD_WATCHDOG_MIN_STEPS=4,
                       HVD_PROFILE_DIR=None)
    for _ in range(10):
        s.observe_step(0.010, origin="t")
    # The negotiation tables charged process 1 during the slow step —
    # the verdict cross-references the telemetry straggler report.
    tele.STRAGGLERS.observe("grad/7", {0: 100.0, 1: 100.5})
    v = s.observe_step(0.300, origin="t")
    assert v is not None and v["verdict"] == "straggler"
    assert v["straggler"]["process"] == 1
    assert v["straggler"]["wait_us"] == pytest.approx(5e5, rel=0.01)


def test_watchdog_stall_attribution(fresh_sentinel, tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    s = fresh_sentinel(HVD_WATCHDOG=1, HVD_WATCHDOG_MIN_STEPS=4,
                       HVD_PROFILE_DIR=None)
    for _ in range(10):
        s.observe_step(0.010, origin="t")
    sen.note_stall("stalled tensors: grad/3 (61s)")
    v = s.observe_step(0.300, origin="t")
    assert v is not None and v["verdict"] == "engine_stall"
    assert "grad/3" in v["stall"]
    assert s.health()["stall"]["reason"].startswith("stalled tensors")


def test_one_step_observed_via_two_origins_counts_once(fresh_sentinel,
                                                       tmp_path,
                                                       monkeypatch):
    """A keras Trainer step is seen twice — the wrapped jit reports its
    dispatch, then the Trainer reports wall time. Capture stepping must
    follow ONE origin (trainer preferred), and one slow step must not
    dump through both watchdogs."""
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    s = fresh_sentinel(HVD_WATCHDOG=1, HVD_WATCHDOG_MIN_STEPS=4,
                       HVD_PROFILE_DIR=None)
    for _ in range(10):  # interleaved, like a real Trainer step
        s.observe_step(0.008, origin="jax.dispatch")
        s.observe_step(0.010, origin="trainer")
    # The capture state machine advanced once per REAL step (plus the
    # one pre-upgrade dispatch observation of the very first step).
    assert s.capture._step <= 11
    assert s._capture_origin == "trainer"
    # One slow step, seen through both lenses: exactly one firing.
    v1 = s.observe_step(0.400, origin="jax.dispatch")
    v2 = s.observe_step(0.402, origin="trainer")
    fired = [v for v in (v1, v2) if v is not None]
    assert len(fired) == 1, (v1, v2)
    dumps = [f for f in os.listdir(tmp_path)
             if f.startswith("hvd_flight")]
    assert len(dumps) == 1, dumps
    total = (s.watchdogs["jax.dispatch"].anomalies
             + s.watchdogs["trainer"].anomalies)
    assert total == 1


def test_telemetry_port_zero_means_disabled(monkeypatch):
    from horovod_tpu.core import telemetry, telemetry_http

    telemetry_http.stop()
    monkeypatch.setattr(telemetry, "_http_started", False)
    monkeypatch.setenv("HVD_TELEMETRY_PORT", "0")
    telemetry._maybe_start_http()
    assert telemetry_http.current_port() is None
    # And a malformed value is ignored, not fatal.
    monkeypatch.setattr(telemetry, "_http_started", False)
    monkeypatch.setenv("HVD_TELEMETRY_PORT", "not-a-port")
    telemetry._maybe_start_http()
    assert telemetry_http.current_port() is None


def test_watchdog_disabled_still_tracks_health(fresh_sentinel):
    s = fresh_sentinel(HVD_WATCHDOG=0)
    assert s.observe_step(10.0, origin="t") is None
    h = s.health()
    assert h["enabled"] is False
    assert h["last_step_age_s"] is not None


def test_health_warns_on_stale_loop(fresh_sentinel):
    """A rank hung inside a compiled-path collective stops observing
    steps entirely — /healthz must degrade on staleness, not just on
    verdicts/stalls."""
    s = fresh_sentinel(HVD_WATCHDOG=1, HVD_WATCHDOG_MIN_STEPS=4)
    for _ in range(6):
        s.observe_step(0.010, origin="t")
    assert s.health()["status"] == "ok"
    s.last_step_wall = time.time() - 120  # 2 min of silence
    h = s.health()
    assert h["status"] == "warn" and h["stale"] is True
    assert h["stale_after_s"] >= 60.0


# ---------------------------------------------------------------------------
# Tier-1 integration: injected slow step on the 8-device mesh
# ---------------------------------------------------------------------------

def test_trainer_slow_step_dumps_and_attributes_once(hvd, tmp_path,
                                                     monkeypatch,
                                                     fresh_sentinel):
    """ISSUE 6 acceptance: one artificially slow training step on the
    8-device CPU mesh yields exactly one flight dump + one attributed
    watchdog verdict — no re-trigger storm."""
    import optax

    import horovod_tpu.keras as hvd_keras
    from horovod_tpu.models import MnistMLP

    rng = np.random.RandomState(0)
    x = rng.randn(256, 8, 8, 1).astype(np.float32)
    y = (rng.rand(256) * 10).astype(np.int32) % 10

    # Build + compile with the suite-default (disabled) sentinel: the
    # first-call compile must not pollute the baseline window.
    t = hvd_keras.Trainer(MnistMLP(hidden=16), optax.sgd(0.1))
    t.fit(x, y, batch_size=2, epochs=1, shuffle=False)

    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    # Wide margins (30× EWMA / 10× p99): ordinary one-core-host jitter
    # (GC pauses, a sibling process) must not fire before the injected
    # step — a spurious firing would open the cooldown and suppress the
    # real anomaly (observed flake: a 14 ms jitter step beat a 2×p99
    # threshold of ~10 ms).
    s = fresh_sentinel(HVD_WATCHDOG=1, HVD_WATCHDOG_MIN_STEPS=8,
                       HVD_WATCHDOG_FACTOR=30, HVD_WATCHDOG_P99_MULT=10,
                       HVD_WATCHDOG_COOLDOWN=1000, HVD_PROFILE_DIR=None)

    # Bypass the _InstrumentedJit wrapper (call the inner jitted object)
    # so ONLY the trainer origin observes this fit: the dispatch origin's
    # µs-scale baseline would make it the jitter-flake magnet.
    real = getattr(t._train_step, "_jitted", t._train_step)
    calls = {"n": 0}

    def injected(*args, **kwargs):
        calls["n"] += 1
        if calls["n"] == 12:  # past the 8-step warmup
            time.sleep(1.5)
        return real(*args, **kwargs)

    t._train_step = injected
    t.fit(x, y, batch_size=2, epochs=1, shuffle=False)  # 16 steps

    wd = s.watchdog("trainer")
    assert wd.steps == 16
    assert wd.anomalies == 1, wd.summary()
    v = s.last_verdict
    assert v is not None and v["origin"] == "trainer"
    assert v["step_s"] > 1.0
    assert v["verdict"] in ("unattributed", "recompile", "straggler",
                            "engine_stall")
    dumps = [f for f in os.listdir(tmp_path)
             if f.startswith("hvd_flight")]
    assert len(dumps) == 1, dumps
    dump = json.load(open(tmp_path / dumps[0]))
    assert "watchdog: trainer step" in dump["reason"]
    assert s.health()["status"] == "warn"


# ---------------------------------------------------------------------------
# Auto-capture: bounded capture -> perf.jsonl record
# ---------------------------------------------------------------------------

def test_autocapture_periodic_appends_perf_jsonl(hvd, tmp_path,
                                                 fresh_sentinel):
    import jax
    import jax.numpy as jnp

    s = fresh_sentinel(HVD_WATCHDOG=0, HVD_PROFILE_DIR=str(tmp_path),
                       HVD_PROFILE_EVERY=6, HVD_PROFILE_STEPS=2)
    f = jax.jit(lambda a: a @ a)
    a = jnp.ones((32, 32))
    for _ in range(9):
        t0 = time.perf_counter()
        f(a).block_until_ready()
        s.observe_step(time.perf_counter() - t0, origin="cap")
    pj = os.path.join(str(tmp_path), "perf.jsonl")
    deadline = time.monotonic() + 60
    rec = None
    while time.monotonic() < deadline and rec is None:
        if os.path.exists(pj):
            lines = open(pj).read().splitlines()
            if lines:
                rec = json.loads(lines[-1])
                break
        time.sleep(0.2)
    assert rec is not None, "no perf.jsonl record appeared"
    assert rec["kind"] == "periodic" and rec["steps"] == 2
    assert rec["step_time_ms"] is not None
    assert os.path.isdir(rec["capture_dir"])
    # The perf.jsonl schema is exactly what perfwatch loads.
    assert pw.load_record(pj)["step_time_ms"] == rec["step_time_ms"]


# ---------------------------------------------------------------------------
# Flight-dump retention cap
# ---------------------------------------------------------------------------

def test_flight_dump_retention_cap(tmp_path, monkeypatch):
    from horovod_tpu.core import timeline as tl

    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("HVD_FLIGHT_KEEP", "3")
    paths = []
    for i in range(7):
        p = tl.dump_flight_recorder([{"name": "X", "ph": "i", "ts": i}],
                                    f"r{i}")
        assert p is not None
        paths.append(p)
        time.sleep(0.002)  # distinct mtimes/wall_us across dumps
    kept = sorted(f for f in os.listdir(tmp_path)
                  if f.startswith("hvd_flight"))
    assert len(kept) == 3, kept
    # The newest dumps survive; the older ones are gone.
    for new in paths[-3:]:
        assert os.path.exists(new), kept
    for old in paths[:4]:
        assert not os.path.exists(old), kept
    # An explicit path (the engines' tests pass one) is never pruned.
    explicit = tmp_path / "explicit.json"
    tl.dump_flight_recorder([], "explicit", path=str(explicit))
    assert explicit.exists()


def test_flight_dump_same_reason_rate_limited(tmp_path, monkeypatch):
    """A poisoned negotiation re-raises the same failure every ~5 ms
    cycle: dump_and_warn must land the first dump and drop same-reason
    repeats inside HVD_FLIGHT_MIN_INTERVAL (distinct reasons still
    land immediately)."""
    import logging

    from horovod_tpu.core import timeline as tl

    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("HVD_FLIGHT_MIN_INTERVAL", "30")
    log = logging.getLogger("test.flight")
    first = tl.dump_and_warn([], "negotiation failed: peer died", 0, log)
    assert first is not None and os.path.exists(first)
    for _ in range(5):
        assert tl.dump_and_warn([], "negotiation failed: peer died",
                                0, log) is None
    other = tl.dump_and_warn([], "stalled tensors: grad/1", 0, log)
    assert other is not None and other != first
    files = [f for f in os.listdir(tmp_path) if f.startswith("hvd_flight")]
    assert len(files) == 2, files


def test_flight_keep_env_parsing(monkeypatch):
    from horovod_tpu.core import timeline as tl

    monkeypatch.delenv("HVD_FLIGHT_KEEP", raising=False)
    assert tl.flight_keep() == 8
    monkeypatch.setenv("HVD_FLIGHT_KEEP", "not-a-number")
    assert tl.flight_keep() == 8
    monkeypatch.setenv("HVD_FLIGHT_KEEP", "0")
    assert tl.flight_keep() == 1  # at least the newest dump survives


# ---------------------------------------------------------------------------
# Profiler: empty captures fail loudly
# ---------------------------------------------------------------------------

def test_profiler_capture_raises_on_empty_capture(tmp_path, monkeypatch):
    from horovod_tpu.utils import profiler

    # A "profiler" that records nothing (the plugin-missing /
    # concurrent-trace failure mode).
    monkeypatch.setattr(profiler, "profile",
                        lambda d: contextlib.nullcontext())
    with pytest.raises(profiler.CaptureError, match="no \\*.xplane.pb"):
        profiler.capture(lambda v: v, 1.0, logdir=str(tmp_path), iters=1)


# ---------------------------------------------------------------------------
# /metrics + /healthz endpoint and the unified stats --json envelope
# ---------------------------------------------------------------------------

@pytest.fixture()
def http_endpoint(fresh_sentinel):
    from horovod_tpu.core import telemetry_http

    fresh_sentinel(HVD_WATCHDOG=0)
    telemetry_http.stop()
    port = telemetry_http.maybe_start(0)  # ephemeral port
    assert port
    yield f"http://127.0.0.1:{port}"
    telemetry_http.stop()


def test_http_endpoint_serves_metrics_and_healthz(http_endpoint):
    import urllib.request

    tele.REGISTRY.counter("sentinel.test_counter").inc(3)
    text = urllib.request.urlopen(
        http_endpoint + "/metrics", timeout=5).read().decode()
    assert "hvd_sentinel_test_counter 3" in text
    resp = urllib.request.urlopen(http_endpoint + "/healthz", timeout=5)
    h = json.loads(resp.read())
    assert resp.status == 200  # no steps yet -> "init", still healthy
    assert h["status"] in ("init", "ok")
    assert "watchdogs" in h and "pid" in h
    # Unknown paths 404 with a hint.
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(http_endpoint + "/nope", timeout=5)
    assert ei.value.code == 404


def test_healthz_degrades_to_503_on_warn(http_endpoint, fresh_sentinel,
                                         tmp_path, monkeypatch):
    import urllib.request

    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    s = fresh_sentinel(HVD_WATCHDOG=1, HVD_WATCHDOG_MIN_STEPS=4,
                       HVD_PROFILE_DIR=None)
    for _ in range(8):
        s.observe_step(0.01, origin="t")
    s.observe_step(0.5, origin="t")  # anomaly -> warn
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(http_endpoint + "/healthz", timeout=5)
    assert ei.value.code == 503
    assert json.loads(ei.value.read())["status"] == "warn"
    # The stats CLI still shows the payload on 503 — the warn state is
    # exactly when the operator queries /healthz.
    from horovod_tpu.utils import stats

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert stats.main([http_endpoint + "/healthz"]) == 0
    assert json.loads(buf.getvalue())["status"] == "warn"
    # --json passes the health document through instead of burying it
    # in an empty-samples envelope.
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert stats.main([http_endpoint + "/healthz", "--json"]) == 0
    h = json.loads(buf.getvalue())
    assert h["status"] == "warn" and "watchdogs" in h


def _stats_json(argv):
    from horovod_tpu.utils import stats

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert stats.main(argv) == 0
    return json.loads(buf.getvalue())


def test_stats_json_shape_identical_across_sources(http_endpoint,
                                                   tmp_path):
    """ISSUE 6 satellite: one envelope shape — {source, target, samples}
    with {name, labels, value} samples — whatever the source."""
    tele.REGISTRY.counter("sentinel.shape_probe").inc()
    # file source
    path = str(tmp_path / "expo.prom")
    from horovod_tpu.core import telemetry

    open(path, "w").write(telemetry.prometheus())
    envs = {
        "file": _stats_json([path, "--json"]),
        "live": _stats_json(["live", "--json"]),
        "http": _stats_json([http_endpoint, "--json"]),
    }
    for src, env in envs.items():
        assert set(env) == {"source", "target", "samples"}, src
        assert env["source"] == src
        assert env["samples"], src
        assert all(set(s) == {"name", "labels", "value"}
                   for s in env["samples"]), src
    probe = "hvd_sentinel_shape_probe"
    for src, env in envs.items():
        assert any(s["name"] == probe for s in env["samples"]), src
    # file and http carry byte-identical sample lists (same exposition
    # text modulo the instant it was read) — compare the probe value.
    get = lambda env: [s["value"] for s in env["samples"]  # noqa: E731
                       if s["name"] == probe][0]
    assert get(envs["file"]) <= get(envs["http"])


def test_stats_watch_works_against_http(http_endpoint, monkeypatch,
                                        capsys):
    from horovod_tpu.utils import stats

    sleeps = []

    def fake_sleep(seconds):
        sleeps.append(seconds)
        if len(sleeps) >= 2:
            raise KeyboardInterrupt

    monkeypatch.setattr(stats.time, "sleep", fake_sleep)
    assert stats.main([http_endpoint, "--watch", "0.25"]) == 0
    out = capsys.readouterr().out
    assert sleeps == [0.25, 0.25]
    assert out.count("hvd_") >= 2  # redrawn at least twice


def test_launcher_exposes_telemetry_port_flag():
    import horovod_tpu.run as launcher

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf), pytest.raises(SystemExit):
        launcher.main(["--help"])
    assert "--telemetry-port-base" in buf.getvalue()


# ---------------------------------------------------------------------------
# Numerics observatory satellites (ISSUE 8): the convergence column
# ---------------------------------------------------------------------------


def test_trend_table_final_loss_column():
    """perf.jsonl records carry final_loss (the sentinel stamps the
    Trainer's last epoch loss); pre-numerics histories simply REFUSE the
    column with '-' — never a crash, never a faked number — and the gate
    never gates on it."""
    recs = [
        {"label": "c1", "value": 2900.0, "final_loss": 2.3456},
        {"label": "c2", "value": 2920.0},  # pre-numerics history record
    ]
    table = pw.trend_table(recs)
    rows = table.splitlines()
    assert "loss" in rows[0]
    assert "2.346" in next(r for r in rows if r.startswith("c1"))
    assert "2.346" not in next(r for r in rows if r.startswith("c2"))
    # The regression gate ignores the convergence column entirely: a
    # loss-less reference vs a loss-carrying current still gates on
    # throughput alone.
    result = pw.gate({"value": 2920.0, "final_loss": 2.3},
                     {"value": 2900.0, "label": "ref"})
    assert result["status"] == "pass"
    assert [c["field"] for c in result["checks"]] == ["value"]


def test_normalize_carries_final_loss_from_perf_jsonl(tmp_path):
    log = tmp_path / "perf.jsonl"
    log.write_text(json.dumps({"value": 100.0, "metric": "m",
                               "final_loss": 0.75}) + "\n"
                   + json.dumps({"value": 101.0, "metric": "m"}) + "\n")
    recs = pw.load_records(str(log))
    assert recs[0]["final_loss"] == 0.75
    assert recs[1]["final_loss"] is None


def test_sentinel_note_loss_feeds_capture_records():
    from horovod_tpu.core import sentinel as sn

    sn.reset_sentinel()
    try:
        s = sn.get_sentinel()
        assert s.last_loss is None
        sn.note_loss(2.5)
        assert s.last_loss == 2.5
        sn.note_loss("not-a-number")  # ignored, never raises
        assert s.last_loss == 2.5
        sn.note_loss(np.float32(1.25))  # host scalars coerce
        assert s.last_loss == 1.25
    finally:
        sn.reset_sentinel()
