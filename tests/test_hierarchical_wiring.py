"""Hierarchical allreduce wired into the DEFAULT data-parallel path
(reference: HOROVOD_HIERARCHICAL_ALLREDUCE as a hot-path runtime knob,
operations.cc:1194-1346, 1760-1778 — not just a library function).

Uses HVD_TWO_TIER_SHAPE to treat the single-process 8-device world as 2
slices of 4 (the same trick as exercising the reference's hierarchical
path under mpirun on one host)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
import horovod_tpu.jax as hvd_jax
from horovod_tpu.common import topology
from horovod_tpu.ops import collectives as C


@pytest.fixture
def two_tier_world(monkeypatch):
    monkeypatch.setenv("HVD_TWO_TIER_SHAPE", "2,4")
    monkeypatch.setenv("HVD_HIERARCHICAL_ALLREDUCE", "1")
    monkeypatch.setenv("HVD_HIERARCHICAL_ALLGATHER", "1")
    hvd.shutdown()
    hvd.init()
    yield hvd
    monkeypatch.undo()
    hvd.shutdown()
    hvd.init()


def test_two_tier_mesh_built(two_tier_world):
    tt = topology.two_tier()
    assert tt is not None
    assert tt.devices.shape == (2, 4)
    assert tt.axis_names == ("dcn", "ici")
    # Same devices, same order as the flat world mesh: rank identity holds.
    assert list(tt.devices.flat) == hvd.devices()


def test_eager_verbs_hierarchical(two_tier_world):
    assert C._hier_allreduce_active()
    x = jnp.arange(4.0)
    np.testing.assert_allclose(np.asarray(hvd.allreduce(x, average=False)),
                               np.asarray(x) * 8)
    np.testing.assert_allclose(np.asarray(hvd.allreduce(x, average=True)),
                               np.asarray(x))
    np.testing.assert_allclose(
        np.asarray(hvd.broadcast(jnp.full((3,), 7.0), root_rank=2)),
        np.full((3,), 7.0))
    g = hvd.allgather(jnp.ones((2, 3)))
    assert g.shape == (16, 3)
    # Distinct per-rank values through the ranked primitives.
    vals = [jnp.full((2,), float(r)) for r in range(8)]
    out = C.ranked_allreduce(C.make_ranked(vals))
    np.testing.assert_allclose(np.asarray(out), np.full((2,), 28.0))
    gath = C.ranked_allgather(C.make_ranked(vals))
    np.testing.assert_allclose(
        np.asarray(gath).ravel(),
        np.repeat(np.arange(8.0), 2))  # global rank order preserved


def test_odd_sizes_pad_path(two_tier_world):
    # 7 elements: not divisible by the ici size 4 -> exercises the
    # pad-to-atomic-unit path (reference: FUSION_BUFFER_ATOMIC_UNIT,
    # operations.cc:712-731).
    x = jnp.arange(7.0)
    np.testing.assert_allclose(np.asarray(hvd.allreduce(x, average=False)),
                               np.asarray(x) * 8)


def test_jit_step_hierarchical(two_tier_world):
    """hvd.jax.jit maps the step over the (dcn, ici) mesh; 'hvd' specs are
    rewritten; in-step allreduce goes hierarchical."""

    @hvd_jax.jit(in_specs=(P(hvd_jax.HVD_AXIS),), out_specs=(P(), P(), P()))
    def f(x):
        from jax import lax

        s = C.allreduce(x[0], average=False)
        return s, lax.psum(1, "ici"), lax.psum(1, "dcn")

    x = jnp.arange(8.0)[:, None] * jnp.ones((8, 4))
    s, ici, dcn = f(x)
    np.testing.assert_allclose(np.asarray(s), np.full((4,), 28.0))
    assert int(ici) == 4 and int(dcn) == 2


def test_distributed_optimizer_hierarchical(two_tier_world):
    """The full DP training-step shape (DistributedOptimizer inside
    hvd.jax.jit) runs hierarchically end to end."""
    import optax

    opt = hvd_jax.DistributedOptimizer(optax.sgd(0.1))
    w0 = jnp.ones((4,))
    opt_state = opt.init(w0)

    @hvd_jax.jit(in_specs=(P(), P(), P(hvd_jax.HVD_AXIS)),
                 out_specs=(P(), P()))
    def step(w, opt_state, x):
        def loss_fn(w):
            return jnp.sum((x[0] @ w) ** 2)

        g = jax.grad(loss_fn)(w)
        updates, opt_state = opt.update(g, opt_state, w)
        return jax.tree.map(lambda p, u: p + u, w, updates), opt_state

    x = jnp.ones((8, 2, 4))
    w1, _ = step(w0, opt_state, x)
    assert np.all(np.isfinite(np.asarray(w1)))
    assert not np.allclose(np.asarray(w1), np.asarray(w0))


def test_engine_path_hierarchical(two_tier_world):
    """The async engine's executor rides the same eager programs, so
    HVD_HIERARCHICAL_ALLREDUCE covers the torch/TF path too."""
    from horovod_tpu.core.engine import Engine

    e = Engine()
    try:
        h = e.allreduce_async("hier_t", np.full((5,), 2.0, np.float32),
                              False)
        np.testing.assert_allclose(e.synchronize(h), np.full((5,), 16.0))
    finally:
        e.shutdown()


def test_flag_off_means_flat(monkeypatch):
    monkeypatch.setenv("HVD_TWO_TIER_SHAPE", "2,4")
    monkeypatch.delenv("HVD_HIERARCHICAL_ALLREDUCE", raising=False)
    hvd.shutdown()
    hvd.init()
    try:
        assert topology.two_tier() is not None  # mesh exists...
        assert not C._hier_allreduce_active()  # ...but the path is off
        x = jnp.arange(4.0)
        np.testing.assert_allclose(
            np.asarray(hvd.allreduce(x, average=False)), np.asarray(x) * 8)
    finally:
        monkeypatch.undo()
        hvd.shutdown()
        hvd.init()
