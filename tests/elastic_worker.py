"""Chaos-monkey elastic training worker — run under the supervisor:

    python -m horovod_tpu.run -np 2 --cpu --elastic -- python elastic_worker.py

Generation 0: the chaos rank (``HVD_TEST_KILL_RANK``, default 1)
SIGKILLs itself mid-epoch — unless ``HVD_TEST_KILL_MODE=none``, where
the chaos comes from the launcher's ``--faults`` injection instead
(e.g. a frozen heartbeat: the rank stays alive but stops beating). The
survivors must take a death verdict, shrink the world in place (epoch
bump, recompile — single- or multi-survivor), resume from the newest
checkpoint and KEEP TRAINING with a continuous loss curve. Killing
rank 0 takes the coordination KV with it: survivors must fail the
lease plane over to the ``HVD_ELASTIC_DIR`` file KV for the verdict.

A later generation (if the supervisor relaunches) resumes from the
newest checkpoint, finishes the remaining epochs, and proves agreement
with ``hvd.check_consistency`` on the regrown mesh.

Per-epoch losses land in ``$HVD_ELASTIC_DIR/losses.rank<N>.jsonl`` so the
pytest driver can assert the curve is continuous (no NaN, no
restart-from-scratch jump)."""

import json
import os
import signal
import sys
import time

RANK = int(os.environ.get("HVD_PROCESS_ID", "0"))
GEN = int(os.environ.get("HVD_ELASTIC_GENERATION", "0"))
EDIR = os.environ["HVD_ELASTIC_DIR"]

KILL_RANK = int(os.environ.get("HVD_TEST_KILL_RANK", "1"))
KILL_MODE = os.environ.get("HVD_TEST_KILL_MODE", "sigkill")
KILL_EPOCH = 1
KILL_BATCH = 5
EPOCHS = int(os.environ.get("HVD_TEST_EPOCHS", "30"))

if os.environ.get("HVD_TEST_DEBUG_TRACE"):
    import faulthandler

    faulthandler.dump_traceback_later(45, repeat=True)

import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
import horovod_tpu.keras as hk  # noqa: E402
from horovod_tpu.core import elastic  # noqa: E402

hvd.init()
print(f"WORLD gen={GEN} rank={hvd.process_index()} "
      f"np={hvd.num_processes()} size={hvd.size()} "
      f"epoch={elastic.get_world().epoch}", flush=True)

import flax.linen as nn  # noqa: E402
import optax  # noqa: E402


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        h = nn.relu(nn.Dense(16)(x))
        return nn.Dense(4)(h)


rng = np.random.default_rng(0)
N, BS = 256, 4
x = rng.normal(size=(N, 8)).astype(np.float32)
w_true = rng.normal(size=(8, 4)).astype(np.float32)
y = (x @ w_true).argmax(axis=1).astype(np.int32)


class ChaosAndLog(hk.callbacks.Callback):
    """Pace epochs (so detection/rejoin timing is exercised mid-run),
    SIGKILL rank 1 mid-epoch in generation 0, and log per-epoch losses
    for the continuity assertion."""

    def on_batch_end(self, batch, logs=None):
        if os.environ.get("HVD_TEST_DEBUG_TRACE"):
            print(f"BATCH gen={GEN} rank={RANK} "
                  f"e{self.trainer._epoch} b{batch}", flush=True)
        if GEN == 0 and RANK == KILL_RANK and KILL_MODE == "sigkill" \
                and self.trainer._epoch == KILL_EPOCH \
                and batch == KILL_BATCH:
            print(f"CHAOS rank={RANK} dying at epoch "
                  f"{self.trainer._epoch} batch {batch}", flush=True)
            os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(0.04)  # pacing: ~0.6 s/epoch of wall time

    def on_epoch_end(self, epoch, logs=None):
        rec = {"gen": GEN, "rank": RANK, "epoch": epoch,
               "world_epoch": elastic.get_world().epoch,
               "size": hvd.size(), "loss": float(logs.get("loss", -1.0)),
               "wall": round(time.time(), 3)}
        with open(os.path.join(EDIR, f"losses.rank{RANK}.jsonl"),
                  "a") as fh:
            fh.write(json.dumps(rec) + "\n")
        print(f"EPOCH gen={GEN} rank={RANK} epoch={epoch} "
              f"size={hvd.size()} loss={rec['loss']:.4f}", flush=True)
        _maybe_pool_check()


_POOLCHECKED = False


def _pool_misses(e):
    if hasattr(e, "pool"):  # python engine
        return e.pool.misses
    import ctypes

    from horovod_tpu.core import native as _nat

    st = _nat.HvdStats()
    e._lib.hvd_engine_get_stats(e._ptr, ctypes.byref(st))
    return int(st.pool_misses) + e._pool.misses


def _maybe_pool_check():
    """Chaos-tier pool hygiene (zero-copy data plane): after the peer's
    SIGKILL forced an in-place shrink — which abandons (and poisons) the
    wedged engine's buffer pool — the lone survivor's FRESH engine must
    round-trip through a working pool with the miss counter flat in
    steady state. Single-survivor worlds only: no cross-rank engine
    coupling inside the chaos scenario."""
    global _POOLCHECKED
    if (_POOLCHECKED or GEN != 0 or hvd.num_processes() != 1
            or elastic.get_world().epoch == 0):
        return
    _POOLCHECKED = True
    from horovod_tpu.core import engine as _eng

    e = _eng.get_engine()
    warm = None
    for i in range(8):
        h = e.allreduce_async(f"poolcheck/{i % 2}",
                              np.full((512,), 1.0, np.float32), False)
        out = e.synchronize(h)
        assert np.isfinite(np.asarray(out)).all()
        if i == 3:
            warm = _pool_misses(e)
    flat = _pool_misses(e) == warm
    assert flat, (warm, _pool_misses(e))
    print(f"POOLCHECK gen={GEN} rank={RANK} misses_flat={flat}",
          flush=True)


trainer = hk.Trainer(MLP(), optax.sgd(0.02, momentum=0.9), rng=0)
x_sample = x[:BS * hvd.local_size()]
initial_epoch = elastic.maybe_restore(trainer, x_sample)
if initial_epoch:
    print(f"RESUMED gen={GEN} rank={RANK} at epoch {initial_epoch} "
          f"world_epoch={elastic.get_world().epoch}", flush=True)

trainer.fit(x, y, batch_size=BS, epochs=EPOCHS, shuffle=False,
            initial_epoch=initial_epoch, callbacks=[ChaosAndLog()])

# Training work is done: announce completion BEFORE the final barriers
# below, while every peer (and the KV host) is still up — a silent exit
# reads as a death to any slower peer.
elastic.get_world().announce_done()

if hvd.num_processes() > 1:
    ok = trainer.check_consistency(tag="post_rejoin")
    assert ok["ok"] is True, ok
    print(f"CONSISTENCY OK gen={GEN} rank={hvd.process_index()} "
          f"size={hvd.size()}", flush=True)

print(f"ELASTIC DONE gen={GEN} rank={RANK} size={hvd.size()} "
      f"np={hvd.num_processes()} "
      f"world_epoch={elastic.get_world().epoch}", flush=True)
sys.stdout.flush()
# Interpreter teardown in a world that lost members would hang in the
# distributed-client destructors; the markers above are the contract.
os._exit(0)
