"""Flash attention kernel vs stock attention (interpret mode on CPU)."""

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models.transformer import (
    causal_attention,
    dot_product_attention,
)
from horovod_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_causal,
)


def _qkv(b=2, s=64, h=2, d=8, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
                 for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv()
    ref = (causal_attention if causal else dot_product_attention)(q, k, v)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_multiblock_vs_singleblock():
    q, k, v = _qkv(s=32)
    a = flash_attention(q, k, v, block_q=32, block_k=32)
    b = flash_attention(q, k, v, block_q=8, block_k=16)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-6)


def test_flash_rejects_bias_and_bad_blocks():
    q, k, v = _qkv(s=16)
    with pytest.raises(NotImplementedError):
        flash_attention(q, k, v, bias=jnp.zeros((1,)))
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v, block_q=10)


def test_flash_as_model_attention_fn():
    """The kernel slots into the transformer via attention_fn."""
    import jax

    from horovod_tpu.models import TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=64, num_layers=1, num_heads=2, hidden_dim=16,
        mlp_dim=32, max_len=16, dtype=jnp.float32, dropout_rate=0.0,
        causal=True, attention_fn=flash_attention_causal)
    m = TransformerLM(cfg)
    tokens = jnp.arange(16)[None] % 64
    variables = m.init(jax.random.PRNGKey(0), tokens)
    out_flash = m.apply(variables, tokens)

    cfg_ref = TransformerConfig(
        vocab_size=64, num_layers=1, num_heads=2, hidden_dim=16,
        mlp_dim=32, max_len=16, dtype=jnp.float32, dropout_rate=0.0,
        causal=True)
    out_ref = TransformerLM(cfg_ref).apply(variables, tokens)
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_ref),
                               rtol=2e-3, atol=2e-4)


def test_flash_in_ulysses():
    """Flash kernel inside Ulysses sequence parallelism."""
    import jax
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    from horovod_tpu import parallel

    devs = jax.devices()[:4]
    mesh = parallel.hybrid_mesh({"sp": 4}, devs)
    q, k, v = _qkv(b=1, s=32, h=4, d=8)
    ref = dot_product_attention(q, k, v)

    def body(q, k, v):
        return parallel.ulysses_attention(
            q, k, v, "sp",
            attention_fn=lambda q, k, v, bias: flash_attention(
                q, k, v, bias, block_q=8, block_k=8))

    spec = P(None, "sp", None, None)
    out = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=spec, check_vma=False)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
