"""Flash attention kernel vs stock attention (interpret mode on CPU)."""

import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models.transformer import (
    causal_attention,
    dot_product_attention,
)
from horovod_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_causal,
)


def _qkv(b=2, s=64, h=2, d=8, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
                 for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv()
    ref = (causal_attention if causal else dot_product_attention)(q, k, v)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_multiblock_vs_singleblock():
    q, k, v = _qkv(s=32)
    a = flash_attention(q, k, v, block_q=32, block_k=32)
    b = flash_attention(q, k, v, block_q=8, block_k=16)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_reference(causal):
    """jax.grad through the kernel (custom_vjp flash backward) vs autodiff
    through the stock attention (the oracle pattern of the reference's
    gradient tests, test_tensorflow.py:321-346 / test_torch.py:351-403)."""
    import jax

    q, k, v = _qkv(b=1, s=32, h=2, d=8)
    ref_fn = causal_attention if causal else dot_product_attention

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=8, block_k=16)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = ref_fn(q, k, v)
        return jnp.sum(o * o)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=2e-3, atol=2e-4, err_msg=name)


def test_flash_value_and_grad_trains():
    """A training step through attention_fn=flash_attention must run and
    reduce the loss (the round-1 kernel crashed under jax.grad)."""
    import jax
    import optax

    from horovod_tpu.models import TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=32, num_layers=1, num_heads=2, hidden_dim=16,
        mlp_dim=32, max_len=16, dtype=jnp.float32, dropout_rate=0.0,
        causal=True,
        attention_fn=lambda q, k, v, bias=None: flash_attention(
            q, k, v, bias, causal=True, block_q=8, block_k=8))
    m = TransformerLM(cfg)
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 32, (2, 16)))
    params = m.init(jax.random.PRNGKey(0), tokens)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            logits = m.apply(p, tokens)
            tgt = jnp.roll(tokens, -1, axis=1)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_flash_grad_multiblock_consistency():
    """Gradients must not depend on the block decomposition."""
    import jax

    q, k, v = _qkv(b=1, s=32, h=1, d=8)

    def loss(q, k, v, bq, bk):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=bq, block_k=bk) ** 2)

    g1 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, 32, 32)
    g2 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v, 8, 16)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_flash_rejects_bias_and_bad_blocks():
    q, k, v = _qkv(s=16)
    with pytest.raises(NotImplementedError):
        flash_attention(q, k, v, bias=jnp.zeros((1,)))
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v, block_q=10)


def test_flash_default_blocks_snap_to_seq():
    """Default block sizes must handle any seq that has a reasonable
    divisor (e.g. 96 = 3*32, not a multiple of the 128 tile)."""
    q, k, v = _qkv(s=96)
    ref = dot_product_attention(q, k, v)
    out = flash_attention(q, k, v)  # no explicit blocks
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_as_model_attention_fn():
    """The kernel slots into the transformer via attention_fn."""
    import jax

    from horovod_tpu.models import TransformerConfig, TransformerLM

    cfg = TransformerConfig(
        vocab_size=64, num_layers=1, num_heads=2, hidden_dim=16,
        mlp_dim=32, max_len=16, dtype=jnp.float32, dropout_rate=0.0,
        causal=True, attention_fn=flash_attention_causal)
    m = TransformerLM(cfg)
    tokens = jnp.arange(16)[None] % 64
    variables = m.init(jax.random.PRNGKey(0), tokens)
    out_flash = m.apply(variables, tokens)

    cfg_ref = TransformerConfig(
        vocab_size=64, num_layers=1, num_heads=2, hidden_dim=16,
        mlp_dim=32, max_len=16, dtype=jnp.float32, dropout_rate=0.0,
        causal=True)
    out_ref = TransformerLM(cfg_ref).apply(variables, tokens)
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_ref),
                               rtol=2e-3, atol=2e-4)


def test_flash_in_ulysses():
    """Flash kernel inside Ulysses sequence parallelism."""
    import jax
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.common.compat import shard_map

    from horovod_tpu import parallel

    devs = jax.devices()[:4]
    mesh = parallel.hybrid_mesh({"sp": 4}, devs)
    q, k, v = _qkv(b=1, s=32, h=4, d=8)
    ref = dot_product_attention(q, k, v)

    def body(q, k, v):
        return parallel.ulysses_attention(
            q, k, v, "sp",
            attention_fn=lambda q, k, v, bias: flash_attention(
                q, k, v, bias, block_q=8, block_k=8))

    spec = P(None, "sp", None, None)
    out = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=spec, check_vma=False)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
