"""Topology basics (reference: horovod/common/__init__.py getters and
test/test_tensorflow.py:44-54 rank/size tests)."""

import pytest


def test_not_initialized_raises():
    from horovod_tpu.common.topology import NotInitializedError, is_initialized
    import horovod_tpu as hvd

    if not is_initialized():
        with pytest.raises(NotInitializedError):
            hvd.size()


def test_init_size_rank(hvd):
    assert hvd.is_initialized()
    assert hvd.size() == 8
    assert hvd.rank() == 0
    assert hvd.local_size() == 8
    assert hvd.local_rank() == 0
    assert hvd.cross_size() == 1
    assert hvd.cross_rank() == 0
    assert hvd.num_processes() == 1
    assert hvd.is_homogeneous()
    assert hvd.mpi_threads_supported()


def test_init_idempotent(hvd):
    hvd.init()
    assert hvd.size() == 8


def test_mesh(hvd):
    m = hvd.mesh()
    assert m.devices.size == 8
    assert m.axis_names == (hvd.device_rank_axis(),)
    assert len(hvd.devices()) == 8


def test_scan_cost_analysis_steps_formula():
    """The on-chip-verified rule for how many scan steps XLA cost
    analysis counts (body once + peeled remainder once; pure-peel when
    unroll >= length)."""
    from horovod_tpu.utils.hardware import scan_cost_analysis_steps as f

    assert f(1, 1) == 1 and f(1, 8) == 1      # no scan emitted
    assert f(50, 1) == 1                       # plain scan: body once
    assert f(50, 2) == 2                       # 25 trips, no remainder
    assert f(50, 4) == 6                       # 12 trips + 2 peeled
    assert f(50, 5) == 5                       # 10 trips, no remainder
    assert f(3, 5) == 3                        # num_trips=0: pure peel
    assert f(5, 2) == 3                        # 2 trips + 1 peeled
