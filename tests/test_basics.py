"""Topology basics (reference: horovod/common/__init__.py getters and
test/test_tensorflow.py:44-54 rank/size tests)."""

import pytest


def test_not_initialized_raises():
    from horovod_tpu.common.topology import NotInitializedError, is_initialized
    import horovod_tpu as hvd

    if not is_initialized():
        with pytest.raises(NotInitializedError):
            hvd.size()


def test_init_size_rank(hvd):
    assert hvd.is_initialized()
    assert hvd.size() == 8
    assert hvd.rank() == 0
    assert hvd.local_size() == 8
    assert hvd.local_rank() == 0
    assert hvd.cross_size() == 1
    assert hvd.cross_rank() == 0
    assert hvd.num_processes() == 1
    assert hvd.is_homogeneous()
    assert hvd.mpi_threads_supported()


def test_init_idempotent(hvd):
    hvd.init()
    assert hvd.size() == 8


def test_mesh(hvd):
    m = hvd.mesh()
    assert m.devices.size == 8
    assert m.axis_names == (hvd.device_rank_axis(),)
    assert len(hvd.devices()) == 8
