"""Sharded weight update (reduce-scatter -> 1/N update -> all-gather;
arxiv 2004.13336) against the replicated-update oracle, on the 8-device
virtual mesh — including the padding contract for param trees whose flat
size is not divisible by the world size, composition with fused_update +
bf16 wire compression, buffer donation of the sharded state, and the
world-size-1 collective elision (subprocess with one device)."""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu.jax as hj
from horovod_tpu.jax import Compression


@pytest.fixture(autouse=True)
def _init(hvd):
    pass


def _params():
    """Flat f32 size 10+3+20 = 33 — NOT divisible by 8, so the scatter
    pads to 40 and the last rank's chunk carries zeros."""
    return {
        "w": jnp.arange(10.0),
        "b": jnp.full((3,), 0.5),
        "k": jnp.linspace(-1.0, 1.0, 20).reshape(4, 5),
    }


def _dyadic_grads(rank_rows, shape_tree, step):
    """Per-rank gradients whose values are small dyadic rationals
    (k/16): every cross-rank sum is exact in f32 REGARDLESS of the
    reduction order, so psum (replicated) and psum_scatter (sharded)
    must agree BITWISE."""

    def one(path_i, leaf):
        n = leaf.size
        base = (np.arange(rank_rows * n).reshape(rank_rows, n)
                % 31 - 15) / 16.0
        return (base + step / 16.0 + path_i / 8.0).astype(np.float32)

    leaves, treedef = jax.tree_util.tree_flatten(shape_tree)
    return treedef, [one(i, l) for i, l in enumerate(leaves)]


def _run_trajectory(make_opt, sharded, hvd, steps=4, compression=None,
                    fused=False, donate=True, params=None):
    """Drive opt.update inside the compiled SPMD step with DISTINCT
    per-rank gradients (fed as rank-stacked arrays) and return the
    resulting params after ``steps`` updates."""
    n = hvd.size()
    params = _params() if params is None else params
    kwargs = {"compression": compression} if compression else {}
    opt = hj.DistributedOptimizer(make_opt(), sharded_update=sharded,
                                  fused_update=fused, **kwargs)
    state = opt.init(params)
    ospec = hj.sharded_state_specs(state) if sharded else P()

    @hj.jit(in_specs=(P(), ospec, P("hvd", None)),
            out_specs=(P(), ospec),
            donate_argnums=(0, 1) if donate else ())
    def step(p, s, gstack):
        # gstack block: (1, total_elems) — this rank's gradient row.
        leaves = jax.tree_util.tree_leaves(p)
        offs, out = 0, []
        for l in leaves:
            out.append(gstack[0, offs: offs + l.size].reshape(l.shape))
            offs += l.size
        g = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(p), out)
        u, s2 = opt.update(g, s, p)
        return optax.apply_updates(p, u), s2

    p, s = params, state
    for t in range(steps):
        _, rows = _dyadic_grads(n, params, t)
        # (n, total): row r = rank r's flat gradient, leaves in flatten
        # order — the step reslices them into the param tree.
        gstack = jnp.asarray(np.concatenate(rows, axis=1))
        p, s = step(p, s, gstack)
    return p


def test_sharded_matches_replicated_sgd_f32_exact(hvd):
    """SGD+momentum in f32 with dyadic gradients: the sharded path must
    match the replicated path BITWISE (dyadic sums are order-exact, and
    the per-shard update is the same arithmetic on a slice)."""
    mk = lambda: optax.sgd(0.5, momentum=0.5)
    ps = _run_trajectory(mk, True, hvd)
    pr = _run_trajectory(mk, False, hvd)
    for k in ps:
        np.testing.assert_array_equal(np.asarray(ps[k]), np.asarray(pr[k]),
                                      err_msg=k)


def test_sharded_fused_bf16_compression_matches_replicated(hvd):
    """sharded_update + fused_update + bf16 wire compression vs the
    replicated path with the same compression: identical precision
    profile (compress before reduce, sum on the bf16 wire), different
    reduction shapes — agreement within bf16 tolerance."""
    mk = lambda: optax.sgd(0.1, momentum=0.9)
    ps = _run_trajectory(mk, True, hvd, compression=Compression.bf16,
                         fused=True)
    pr = _run_trajectory(mk, False, hvd, compression=Compression.bf16,
                         fused=True)
    for k in ps:
        np.testing.assert_allclose(np.asarray(ps[k]), np.asarray(pr[k]),
                                   rtol=2e-2, atol=2e-2, err_msg=k)


def test_sharded_adam_matches_replicated(hvd):
    """Adam's rsqrt makes bitwise equality unattainable, but the sharded
    trajectory must track the replicated one tightly (the scalar count
    state stays replicated, the m/v buffers shard)."""
    mk = lambda: optax.adam(1e-2)
    ps = _run_trajectory(mk, True, hvd)
    pr = _run_trajectory(mk, False, hvd)
    for k in ps:
        np.testing.assert_allclose(np.asarray(ps[k]), np.asarray(pr[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_eager_sharded_matches_eager_replicated(hvd):
    """The eager fallback (allreduce + full-buffer update) must produce
    the replicated trajectory — elementwise updates make the full update
    the concatenation of the per-shard updates."""

    def run(sharded):
        params = _params()
        opt = hj.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                                      sharded_update=sharded)
        s = opt.init(params)
        p = params
        for t in range(3):
            g = jax.tree_util.tree_map(
                lambda l: jnp.full(l.shape, 0.25 * (t + 1)), params)
            u, s = opt.update(g, s, p)
            p = optax.apply_updates(p, u)
        return p

    ps, pr = run(True), run(False)
    for k in ps:
        np.testing.assert_allclose(np.asarray(ps[k]), np.asarray(pr[k]),
                                   rtol=1e-6, err_msg=k)


def test_sharded_state_specs(hvd):
    """Flat padded buffers ride P('hvd'); scalar bookkeeping (adam's
    count) stays replicated P()."""
    params = _params()
    opt = hj.DistributedOptimizer(optax.adam(1e-3), sharded_update=True)
    state = opt.init(params)
    specs = hj.sharded_state_specs(state)
    leaves = jax.tree_util.tree_leaves(
        state, is_leaf=lambda x: isinstance(x, jnp.ndarray))
    spec_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P))
    assert len(leaves) == len(spec_leaves)
    n = hvd.size()
    for leaf, spec in zip(leaves, spec_leaves):
        if jnp.ndim(leaf) >= 1:
            assert leaf.shape[0] % n == 0, "buffers must pad to N"
            assert spec == P("hvd"), (leaf.shape, spec)
        else:
            assert spec == P(), (leaf.shape, spec)


def test_sharded_update_init_pads_to_world_multiple(hvd):
    """init()'s per-dtype buffers are zero-padded to a world-size
    multiple — 33 f32 elements become 40 on the 8-device mesh."""
    params = _params()
    sharded = hj.shard_update(optax.sgd(0.1, momentum=0.9))
    state = sharded.init(params)
    bufs = [l for l in jax.tree_util.tree_leaves(state) if jnp.ndim(l) == 1]
    assert bufs and all(b.shape[0] == 40 for b in bufs), [
        b.shape for b in bufs]


def test_sharded_update_rejects_accumulation(hvd):
    """sharded_update's flat-buffer state cannot be told apart from the
    accumulation wrapper's param-structured accumulators by
    sharded_state_specs — the combination must refuse loudly instead of
    silently sharding an accumulator."""
    with pytest.raises(ValueError, match="backward_passes_per_step"):
        hj.DistributedOptimizer(optax.sgd(0.1), sharded_update=True,
                                backward_passes_per_step=2)


def test_accumulation_skip_returns_cached_zero_tree(hvd):
    """The non-boundary microstep must not materialize a fresh
    param-sized zero tree: the skip path returns the SAME cached
    buffers on every call (the updates contract promises values, not
    fresh arrays), and the boundary update is unchanged."""
    params = {"w": jnp.ones((5,)), "b": jnp.zeros(())}
    opt = hj.DistributedOptimizer(optax.sgd(0.1),
                                  backward_passes_per_step=3)
    state = opt.init(params)
    g = {"w": jnp.ones((5,)), "b": jnp.ones(())}
    u1, state = opt.update(g, state, params)
    u2, state = opt.update(g, state, params)
    for a, b in zip(jax.tree_util.tree_leaves(u1),
                    jax.tree_util.tree_leaves(u2)):
        assert a is b, "skip path must reuse one zero tree"
        np.testing.assert_array_equal(np.asarray(a), np.zeros(a.shape))
    u3, state = opt.update(g, state, params)
    np.testing.assert_allclose(np.asarray(u3["w"]), -0.1 * np.ones(5),
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# state_dtype='bf16' — bf16 resident state with f32 master shards
# (HBM diet round 2, arxiv 2004.13336 §4)
# ---------------------------------------------------------------------------


def _bf16_params():
    """The non-divisible tree (flat 33 -> padded 40 on 8 devices), cast
    to the bf16 resident layout."""
    return jax.tree_util.tree_map(lambda l: l.astype(jnp.bfloat16),
                                  _params())


def _bf16_rounded_f32_params():
    """The SAME starting point as :func:`_bf16_params` at f32 width —
    what the f32 oracle must start from for a fair trajectory comparison
    (the linspace leaf is not bf16-exact, so the initial cast already
    rounds; the masters derive from the *rounded* residents)."""
    return jax.tree_util.tree_map(lambda l: l.astype(jnp.float32),
                                  _bf16_params())


def _run_mixed_trajectory(make_opt, hvd, steps=4):
    """Drive the state_dtype='bf16' fused-sharded step with the SAME
    per-rank dyadic gradients as :func:`_run_trajectory` and return
    (resident params, final opt state)."""
    n = hvd.size()
    params = _bf16_params()
    opt = hj.DistributedOptimizer(make_opt(), sharded_update=True,
                                  state_dtype="bf16")
    state = opt.init(params)
    ospec = hj.sharded_state_specs(state)

    @hj.jit(in_specs=(P(), ospec, P("hvd", None)),
            out_specs=(P(), ospec), donate_argnums=(0, 1))
    def step(p, s, gstack):
        leaves = jax.tree_util.tree_leaves(p)
        offs, out = 0, []
        for l in leaves:
            out.append(gstack[0, offs: offs + l.size].reshape(l.shape))
            offs += l.size
        g = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(p), out)
        u, s2 = opt.update(g, s, p)
        return optax.apply_updates(p, u), s2

    p, s = params, state
    for t in range(steps):
        _, rows = _dyadic_grads(n, _params(), t)
        p, s = step(p, s, jnp.asarray(np.concatenate(rows, axis=1)))
    return p, s


def _masters_flat(state):
    """The f32 master buffer, unpadded (flat 33 of the padded 40)."""
    assert hj.has_master_shards(state)
    buf = np.asarray(state["master"]["bfloat16"], dtype=np.float32)
    return buf[:33]


def _oracle_flat(params):
    """The replicated-f32 oracle params, flattened in layout order."""
    return np.concatenate([np.asarray(l, dtype=np.float32).ravel()
                           for l in jax.tree_util.tree_leaves(params)])


def test_bf16_state_sgd_masters_match_replicated_f32_bitwise(hvd):
    """Shard-exact per 2004.13336 §4: the dyadic gradients are exactly
    representable in bf16 and their 8-way sums fit bf16's significand,
    so the bf16 reduce-scatter wire loses nothing — the f32 master
    trajectory must match replicated-f32 SGD BITWISE. (Momentum-less:
    a momentum trace is *stored* bf16 under the policy, so any stateful
    transform picks up the designed storage rounding — covered by the
    tolerance-bounded Adam test and the 1-ulp resident test below.)"""
    mk = lambda: optax.sgd(0.5)
    _, s = _run_mixed_trajectory(mk, hvd)
    pr = _run_trajectory(mk, False, hvd,
                         params=_bf16_rounded_f32_params())
    np.testing.assert_array_equal(_masters_flat(s), _oracle_flat(pr))


def test_bf16_state_adam_tracks_replicated(hvd):
    """Adam under the policy stores m/v in bf16 between steps (the
    rounding bf16 introduces) — tolerance-bounded against replicated
    f32 Adam, not bitwise."""
    mk = lambda: optax.adam(1e-2)
    _, s = _run_mixed_trajectory(mk, hvd)
    pr = _run_trajectory(mk, False, hvd,
                         params=_bf16_rounded_f32_params())
    np.testing.assert_allclose(_masters_flat(s), _oracle_flat(pr),
                               rtol=1e-2, atol=1e-2)


def test_bf16_residents_track_masters_within_one_ulp(hvd):
    """Residents stay bf16 and sit within 1 bf16 ulp of cast(master):
    the delta re-anchors on the actual resident values every step, so
    the rounding never accumulates."""
    p, s = _run_mixed_trajectory(lambda: optax.sgd(0.5, momentum=0.5),
                                 hvd)
    flat_res = np.concatenate(
        [np.asarray(l, dtype=np.float32).ravel()
         for l in jax.tree_util.tree_leaves(p)])
    for l in jax.tree_util.tree_leaves(p):
        assert l.dtype == jnp.bfloat16
    master = _masters_flat(s)
    cast = np.asarray(jnp.asarray(master).astype(jnp.bfloat16)
                      .astype(jnp.float32))
    # one bf16 ulp at the master's magnitude (eps = 2^-8 per mantissa
    # step; x2 headroom for the double rounding of apply_updates)
    tol = np.maximum(np.abs(master), 1e-3) * 2.0 ** -7
    np.testing.assert_array_less(np.abs(flat_res - cast), tol + 1e-6)


def test_bf16_state_layout_dtypes_and_specs(hvd):
    """The mixed state layout: f32 masters + storage-dtype inner, every
    padded buffer riding P('hvd'), scalar bookkeeping replicated."""
    params = _bf16_params()
    opt = hj.DistributedOptimizer(optax.adam(1e-3), sharded_update=True,
                                  state_dtype="bf16")
    state = opt.init(params)
    assert hj.has_master_shards(state)
    for b in state["master"].values():
        assert b.dtype == jnp.float32 and b.shape[0] % hvd.size() == 0
    # Adam's m/v buffers are *stored* bf16; the count scalar stays exact.
    inner_bufs = [l for l in jax.tree_util.tree_leaves(state["inner"])
                  if jnp.ndim(l) >= 1]
    assert inner_bufs and all(b.dtype == jnp.bfloat16 for b in inner_bufs)
    specs = hj.sharded_state_specs(state)
    leaves = jax.tree_util.tree_leaves(state)
    spec_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P))
    assert len(leaves) == len(spec_leaves)
    for leaf, spec in zip(leaves, spec_leaves):
        assert spec == (P("hvd") if jnp.ndim(leaf) >= 1 else P())


def test_bf16_state_requires_params_on_update(hvd):
    """The resident-delta re-anchoring needs the resident values — an
    update call without params must refuse loudly."""
    params = _bf16_params()
    opt = hj.shard_update(optax.sgd(0.1), state_dtype="bf16")
    state = opt.init(params)
    with pytest.raises(ValueError, match="needs params"):
        opt.update(params, state)


def test_bf16_state_rejects_unknown_spelling(hvd):
    with pytest.raises(ValueError, match="state_dtype"):
        hj.DistributedOptimizer(optax.sgd(0.1), state_dtype="int8")


def test_state_dtype_f32_spellings_mean_off(hvd):
    """'f32'/'float32'/None AND the dtype objects jnp.float32/np.float32
    all disable the policy — config code that resolves dtype names to
    objects must not crash on the 'explicitly off' spelling."""
    for off in (None, "f32", "float32", jnp.float32, np.float32,
                jnp.dtype("float32")):
        assert hj.canonical_state_dtype(off) is None
    assert hj.canonical_state_dtype(jnp.bfloat16) == jnp.bfloat16


def test_bf16_state_update_honors_lr_scale(hvd):
    """The reserved ``lr_scale`` extra arg scales the MASTER trajectory
    (keras LR warmup/schedule wiring): under the mixed layout the
    masters advance inside ``update`` and the return value is only a
    re-anchored resident delta, so a caller-side ``updates * scale``
    cannot work — the scale must ride into the epilogue. Plain SGD from
    zero masters makes the check exact (f32 `0 + u` is `u` bitwise):
    masters must move by exactly scale * (lr * grad)."""
    params = jax.tree_util.tree_map(jnp.zeros_like, _bf16_params())
    grads = jax.tree_util.tree_map(
        lambda l: jnp.full(l.shape, 0.5, l.dtype), params)
    opt = hj.shard_update(optax.sgd(0.1), average=False,
                          state_dtype="bf16")

    state = opt.init(params)
    _, s_full = opt.update(grads, state, params)
    state = opt.init(params)
    upd_half, s_half = opt.update(grads, state, params,
                                  lr_scale=jnp.float32(0.5))
    m0 = _masters_flat(opt.init(params))
    d_full = _masters_flat(s_full) - m0
    d_half = _masters_flat(s_half) - m0
    np.testing.assert_array_equal(d_half, 0.5 * d_full)
    assert np.any(d_full != 0.0)

    # lr_scale=0 freezes the trajectory: masters unchanged, resident
    # delta all-zero (residents already sit at bf16(master)).
    state = opt.init(params)
    upd0, s0 = opt.update(grads, state, params, lr_scale=jnp.float32(0.0))
    np.testing.assert_array_equal(_masters_flat(s0), m0)
    for l in jax.tree_util.tree_leaves(upd0):
        np.testing.assert_array_equal(np.asarray(l, np.float32), 0.0)


def test_bf16_state_hlo_no_full_width_f32(hvd):
    """The HLO pin for the fused epilogue (HBM diet round 2): at the
    program (StableHLO) level every reduce-scatter/all-gather runs at
    bf16 — the gradient round-trip between the collective and the update
    never widens to f32 at full width — and the compiled per-device
    entry carries NO full-width f32 buffer: masters and inner state
    arrive as the f32[5] 1/N shard of the padded f32[40], residents as
    bf16. (Full-buffer f32 ops inside the compiled text are XLA:CPU's
    bf16-collective legalization, absent on TPU — the pin is the program
    and the entry signature, as docs/benchmarks.md records.)"""
    params = _bf16_params()
    opt = hj.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                                  sharded_update=True, state_dtype="bf16")
    state = opt.init(params)
    ospec = hj.sharded_state_specs(state)

    @hj.jit(in_specs=(P(), ospec, P()), out_specs=(P(), ospec))
    def step(p, s, g):
        u, s2 = opt.update(g, s, p)
        return optax.apply_updates(p, u), s2

    lowered = step.lower(params, state, params)
    txt = lowered.as_text()
    import re as _re

    # The op's type signature closes its (possibly multi-line) region:
    # `}) : (tensor<40xbf16>) -> tensor<5xbf16>` for the reduce-scatter,
    # single-line `... : (tensor<5xbf16>) -> tensor<40xbf16>` for the
    # all-gather.
    sigs = _re.findall(
        r'stablehlo\.(reduce_scatter|all_gather)"'
        r'.*?:\s*\((tensor<[^)]*>)\)\s*->\s*(tensor<[^>]+>)',
        txt, _re.S)
    assert sigs, "expected collectives in the 8-device program"
    assert {op for op, _, _ in sigs} == {"reduce_scatter", "all_gather"}
    for op, operand, result in sigs:
        assert "bf16" in operand and "bf16" in result, (op, operand,
                                                        result)
        assert "f32" not in operand and "f32" not in result, (
            op, operand, result)
    ctext = lowered.compile().as_text()
    entry = next(ln for ln in ctext.splitlines() if "ENTRY" in ln)
    assert "f32[40]" not in entry, entry   # no full-width f32 in/out
    assert "f32[5]" in entry, entry        # the 1/N master shard
    assert "bf16" in entry, entry          # bf16 residents


def test_accumulation_skip_zero_tree_honors_state_dtype(hvd):
    """A skipped microbatch under the policy must hand back zeros at the
    policy dtype — not a full-width f32 tree — even when the incoming
    grads are wider f32; the accumulators stay at the policy dtype too."""
    params = {"w": jnp.ones((5,), jnp.bfloat16),
              "b": jnp.zeros((), jnp.bfloat16)}
    opt = hj.DistributedOptimizer(optax.sgd(0.1),
                                  backward_passes_per_step=3,
                                  state_dtype="bf16")
    state = opt.init(params)
    for l in jax.tree_util.tree_leaves(state["acc"]):
        assert l.dtype == jnp.bfloat16
    g32 = {"w": jnp.ones((5,), jnp.float32),
           "b": jnp.ones((), jnp.float32)}
    u1, state = opt.update(g32, state, params)
    for l in jax.tree_util.tree_leaves(u1):
        assert l.dtype == jnp.bfloat16, "skip zeros must be policy dtype"
        np.testing.assert_array_equal(np.asarray(l, np.float32),
                                      np.zeros(l.shape))
    for l in jax.tree_util.tree_leaves(state["acc"]):
        assert l.dtype == jnp.bfloat16, "acc must not promote to f32"
    u2, state = opt.update(g32, state, params)
    u3, state = opt.update(g32, state, params)
    # Boundary update arrives at the param width with the accumulated
    # gradient applied (3 microbatches of ones, averaged by count).
    assert all(l.dtype == jnp.bfloat16
               for l in jax.tree_util.tree_leaves(u3))
    np.testing.assert_allclose(
        np.asarray(u3["w"], np.float32), -0.1 * np.ones(5), rtol=1e-2)


def test_accumulation_skip_tolerates_uncast_f32_params(hvd):
    """A caller that ignores the 'cast residents first' precondition
    (f32 params under a bf16 policy) must still get a working jitted
    accumulation step: the skip branch's zeros follow the PARAM width —
    matching the apply branch's state_storage cast — so lax.cond's
    branch types agree (a policy-dtype zero tree here raised `true_fun
    and false_fun output must have identical types` naming neither
    state_dtype nor the missing cast). hvd.jit (not plain jax.jit, whose
    axis-less trace collectives refuse by design) so count is a tracer
    and the lax.cond path — not the eager concrete-count branch — is
    what's exercised."""
    params = {"w": jnp.ones((5,), jnp.float32)}
    opt = hj.DistributedOptimizer(optax.sgd(0.1),
                                  backward_passes_per_step=2,
                                  state_dtype="bf16")
    state = opt.init(params)
    g = {"w": jnp.ones((5,), jnp.float32)}

    @hj.jit(in_specs=(P(), P(), P()), out_specs=(P(), P()))
    def step(g, state, params):
        return opt.update(g, state, params)

    u1, state = step(g, state, params)      # skip microbatch
    assert u1["w"].dtype == jnp.float32     # param width, both branches
    np.testing.assert_array_equal(np.asarray(u1["w"]), np.zeros(5))
    u2, state = step(g, state, params)      # boundary
    assert u2["w"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(u2["w"]), -0.1 * np.ones(5),
                               rtol=1e-2)


@pytest.mark.parametrize("grad_dtype", ["bfloat16", "float32"])
def test_accumulation_skip_without_params_under_policy(hvd, grad_dtype):
    """The standard optax convention — ``update(grads, state)`` with NO
    params — must keep working under the policy with accumulation: the
    apply branch's updates follow the width of the MEAN the inner update
    sees (the policy-dtype accumulator; state_storage's grad-width rule,
    since the f32-loaded momentum trace would otherwise promote them to
    f32) and the skip branch's zeros key off the accumulator too, so
    lax.cond's branch types agree — for policy-width AND for wider f32
    grads (which ``acc_update`` casts back to the accumulator width)."""
    params = {"w": jnp.ones((8,), jnp.bfloat16)}
    opt = hj.DistributedOptimizer(optax.sgd(0.01, momentum=0.9),
                                  fused_update=True, state_dtype="bf16",
                                  backward_passes_per_step=2)
    state = opt.init(params)
    g = {"w": jnp.full((8,), 0.5, grad_dtype)}

    @hj.jit(in_specs=(P(), P()), out_specs=(P(), P()))
    def step(g, state):
        return opt.update(g, state)

    u1, state = step(g, state)              # skip microbatch
    assert u1["w"].dtype == jnp.bfloat16    # accumulator width, both branches
    np.testing.assert_array_equal(np.asarray(u1["w"], np.float32),
                                  np.zeros(8))
    u2, state = step(g, state)              # boundary
    assert u2["w"].dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(u2["w"], np.float32),
                               -0.01 * 0.5 * np.ones(8), rtol=1e-2)


def test_save_restore_step_equivalence_bf16_masters(hvd):
    """The checkpoint contract at the optimizer level: persisting the
    mixed state and rebuilding residents from the masters
    (resident == cast(master) bitwise), then stepping, yields the SAME
    master trajectory as the uninterrupted run — shard-exact for SGD
    with dyadic gradients; residents agree within the 1-ulp re-anchor
    band."""
    mk = lambda: optax.sgd(0.5, momentum=0.5)
    n = hvd.size()

    def drive(p, s, opt, steps, t0=0):
        ospec = hj.sharded_state_specs(s)

        @hj.jit(in_specs=(P(), ospec, P("hvd", None)),
                out_specs=(P(), ospec))
        def step(p, s, gstack):
            leaves = jax.tree_util.tree_leaves(p)
            offs, out = 0, []
            for l in leaves:
                out.append(gstack[0, offs: offs + l.size].reshape(l.shape))
                offs += l.size
            g = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(p), out)
            u, s2 = opt.update(g, s, p)
            return optax.apply_updates(p, u), s2

        for t in range(t0, t0 + steps):
            _, rows = _dyadic_grads(n, _params(), t)
            p, s = step(p, s, jnp.asarray(np.concatenate(rows, axis=1)))
        return p, s

    opt = hj.DistributedOptimizer(mk(), sharded_update=True,
                                  state_dtype="bf16")
    params = _bf16_params()
    state = opt.init(params)
    # Uninterrupted: 4 steps straight through.
    pa, sa = drive(params, state, opt, 4)
    # Interrupted: 2 steps, "save" (device_get), restore residents from
    # masters, 2 more steps.
    pb, sb = drive(params, state, opt, 2)
    saved = jax.device_get(sb)
    restored_p = hj.resident_from_masters(saved, pb)
    # Restore invariant: residents rebuilt BITWISE as cast(master).
    for r, l in zip(jax.tree_util.tree_leaves(restored_p),
                    jax.tree_util.tree_leaves(pb)):
        assert r.dtype == jnp.bfloat16 and r.shape == l.shape
    pc, sc = drive(jax.tree_util.tree_map(jnp.asarray, restored_p),
                   jax.tree_util.tree_map(jnp.asarray, saved),
                   opt, 2, t0=2)
    np.testing.assert_array_equal(_masters_flat(sc), _masters_flat(sa))
    for ka, kc in zip(jax.tree_util.tree_leaves(pa),
                      jax.tree_util.tree_leaves(pc)):
        np.testing.assert_allclose(
            np.asarray(ka, np.float32), np.asarray(kc, np.float32),
            rtol=2.0 ** -6)


def test_world_size_one_elides_collectives(hvd):
    """A 1-rank world compiles the DistributedOptimizer step with NO
    all-reduce and NO pack/unpack concatenate — the r5 one-chip bench
    paid a full extra HBM round trip of the gradient tree for an
    identity reduction (docs/benchmarks.md 'HBM diet'). Subprocess: the
    suite's own world is 8 virtual devices."""
    code = """
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp, numpy as np, optax
import horovod_tpu as hvd, horovod_tpu.jax as hj
from jax.sharding import PartitionSpec as P
hvd.init()
assert hvd.size() == 1, hvd.size()
x = jnp.arange(8.0)
np.testing.assert_array_equal(np.asarray(hvd.allreduce(x)), np.asarray(x))
np.testing.assert_array_equal(np.asarray(hvd.broadcast(x, 0)), np.asarray(x))
np.testing.assert_array_equal(np.asarray(hvd.reducescatter(x)), np.asarray(x))
# No lossy wire cast either: bf16 compression short-circuits at size 1.
y = jnp.float32(1.0) + jnp.float32(1e-4)
np.testing.assert_array_equal(
    np.asarray(hj.allreduce(y[None], compression=hj.Compression.bf16)),
    np.asarray(y[None]))
opt = hj.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                              fused_update=True)
params = {"a": jnp.ones((64, 64)), "b": jnp.ones((7,))}
s = opt.init(params)
def step(p, s, g):
    u, s2 = opt.update(g, s, p)
    return optax.apply_updates(p, u), s2
f = hj.jit(step, in_specs=(P(), P(), P()), out_specs=(P(), P()))
txt = f.lower(params, s, params).compile().as_text()
assert "all-reduce" not in txt, "size-1 allreduce must be elided"
assert "concatenate" not in txt, "size-1 grouped pack must be elided"
# state_dtype='bf16' at world size 1: the mixed master/inner layout
# still builds, and every collective (reduce-scatter, all-gather,
# all-reduce) elides the same way.
opt2 = hj.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                               sharded_update=True, state_dtype="bf16")
p2 = {"a": jnp.ones((64, 64), jnp.bfloat16),
      "b": jnp.ones((7,), jnp.bfloat16)}
s2 = opt2.init(p2)
assert isinstance(s2, dict) and set(s2) == {"master", "inner"}, s2
def step2(p, s, g):
    u, s3 = opt2.update(g, s, p)
    return optax.apply_updates(p, u), s3
f2 = hj.jit(step2, in_specs=(P(), P(), P()), out_specs=(P(), P()))
txt2 = f2.lower(p2, s2, p2).compile().as_text()
for op in ("all-reduce", "reduce-scatter", "all-gather"):
    assert op not in txt2, op + " must be elided at world size 1"
# Quantized policy (ISSUE 12): world size 1 elides EVERYTHING including
# quantize/dequantize — the int8 step's program carries no s8 payload,
# no all-to-all, and its numbers match the uncompressed step BITWISE
# (a surviving quantize would be a lossy round trip for nothing).
p3 = {"a": jnp.linspace(0.1, 1.7, 96).reshape(8, 12),
      "b": jnp.full((7,), 0.123)}
g3 = jax.tree_util.tree_map(lambda l: l * 0.01, p3)
outs = {}
for nm, comp in (("none", hj.Compression.none),
                 ("int8", hj.Compression.int8_ef)):
    opt3 = hj.DistributedOptimizer(optax.sgd(0.1), sharded_update=True,
                                   compression=comp)
    s3 = opt3.init(p3)
    def step3(p, s, g, _opt=opt3):
        u, s4 = _opt.update(g, s, p)
        return optax.apply_updates(p, u), s4
    f3 = hj.jit(step3, in_specs=(P(), P(), P()), out_specs=(P(), P()))
    if nm == "int8":
        txt3 = f3.lower(p3, s3, g3).compile().as_text()
        for tok in ("all-to-all", "all-gather", "s8["):
            assert tok not in txt3, tok + " must be elided at size 1"
    outs[nm], _ = f3(p3, s3, g3)
for ka, kb in zip(jax.tree_util.tree_leaves(outs["none"]),
                  jax.tree_util.tree_leaves(outs["int8"])):
    np.testing.assert_array_equal(np.asarray(ka), np.asarray(kb))
print("ELIDED-OK")
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # Strip the 8-device flag the suite's conftest forces: this world
    # must see exactly one device.
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", code], env=env, cwd=repo,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "ELIDED-OK" in proc.stdout
