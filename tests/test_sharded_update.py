"""Sharded weight update (reduce-scatter -> 1/N update -> all-gather;
arxiv 2004.13336) against the replicated-update oracle, on the 8-device
virtual mesh — including the padding contract for param trees whose flat
size is not divisible by the world size, composition with fused_update +
bf16 wire compression, buffer donation of the sharded state, and the
world-size-1 collective elision (subprocess with one device)."""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu.jax as hj
from horovod_tpu.jax import Compression


@pytest.fixture(autouse=True)
def _init(hvd):
    pass


def _params():
    """Flat f32 size 10+3+20 = 33 — NOT divisible by 8, so the scatter
    pads to 40 and the last rank's chunk carries zeros."""
    return {
        "w": jnp.arange(10.0),
        "b": jnp.full((3,), 0.5),
        "k": jnp.linspace(-1.0, 1.0, 20).reshape(4, 5),
    }


def _dyadic_grads(rank_rows, shape_tree, step):
    """Per-rank gradients whose values are small dyadic rationals
    (k/16): every cross-rank sum is exact in f32 REGARDLESS of the
    reduction order, so psum (replicated) and psum_scatter (sharded)
    must agree BITWISE."""

    def one(path_i, leaf):
        n = leaf.size
        base = (np.arange(rank_rows * n).reshape(rank_rows, n)
                % 31 - 15) / 16.0
        return (base + step / 16.0 + path_i / 8.0).astype(np.float32)

    leaves, treedef = jax.tree_util.tree_flatten(shape_tree)
    return treedef, [one(i, l) for i, l in enumerate(leaves)]


def _run_trajectory(make_opt, sharded, hvd, steps=4, compression=None,
                    fused=False, donate=True):
    """Drive opt.update inside the compiled SPMD step with DISTINCT
    per-rank gradients (fed as rank-stacked arrays) and return the
    resulting params after ``steps`` updates."""
    n = hvd.size()
    params = _params()
    kwargs = {"compression": compression} if compression else {}
    opt = hj.DistributedOptimizer(make_opt(), sharded_update=sharded,
                                  fused_update=fused, **kwargs)
    state = opt.init(params)
    ospec = hj.sharded_state_specs(state) if sharded else P()

    @hj.jit(in_specs=(P(), ospec, P("hvd", None)),
            out_specs=(P(), ospec),
            donate_argnums=(0, 1) if donate else ())
    def step(p, s, gstack):
        # gstack block: (1, total_elems) — this rank's gradient row.
        leaves = jax.tree_util.tree_leaves(p)
        offs, out = 0, []
        for l in leaves:
            out.append(gstack[0, offs: offs + l.size].reshape(l.shape))
            offs += l.size
        g = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(p), out)
        u, s2 = opt.update(g, s, p)
        return optax.apply_updates(p, u), s2

    p, s = params, state
    for t in range(steps):
        _, rows = _dyadic_grads(n, params, t)
        # (n, total): row r = rank r's flat gradient, leaves in flatten
        # order — the step reslices them into the param tree.
        gstack = jnp.asarray(np.concatenate(rows, axis=1))
        p, s = step(p, s, gstack)
    return p


def test_sharded_matches_replicated_sgd_f32_exact(hvd):
    """SGD+momentum in f32 with dyadic gradients: the sharded path must
    match the replicated path BITWISE (dyadic sums are order-exact, and
    the per-shard update is the same arithmetic on a slice)."""
    mk = lambda: optax.sgd(0.5, momentum=0.5)
    ps = _run_trajectory(mk, True, hvd)
    pr = _run_trajectory(mk, False, hvd)
    for k in ps:
        np.testing.assert_array_equal(np.asarray(ps[k]), np.asarray(pr[k]),
                                      err_msg=k)


def test_sharded_fused_bf16_compression_matches_replicated(hvd):
    """sharded_update + fused_update + bf16 wire compression vs the
    replicated path with the same compression: identical precision
    profile (compress before reduce, sum on the bf16 wire), different
    reduction shapes — agreement within bf16 tolerance."""
    mk = lambda: optax.sgd(0.1, momentum=0.9)
    ps = _run_trajectory(mk, True, hvd, compression=Compression.bf16,
                         fused=True)
    pr = _run_trajectory(mk, False, hvd, compression=Compression.bf16,
                         fused=True)
    for k in ps:
        np.testing.assert_allclose(np.asarray(ps[k]), np.asarray(pr[k]),
                                   rtol=2e-2, atol=2e-2, err_msg=k)


def test_sharded_adam_matches_replicated(hvd):
    """Adam's rsqrt makes bitwise equality unattainable, but the sharded
    trajectory must track the replicated one tightly (the scalar count
    state stays replicated, the m/v buffers shard)."""
    mk = lambda: optax.adam(1e-2)
    ps = _run_trajectory(mk, True, hvd)
    pr = _run_trajectory(mk, False, hvd)
    for k in ps:
        np.testing.assert_allclose(np.asarray(ps[k]), np.asarray(pr[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_eager_sharded_matches_eager_replicated(hvd):
    """The eager fallback (allreduce + full-buffer update) must produce
    the replicated trajectory — elementwise updates make the full update
    the concatenation of the per-shard updates."""

    def run(sharded):
        params = _params()
        opt = hj.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                                      sharded_update=sharded)
        s = opt.init(params)
        p = params
        for t in range(3):
            g = jax.tree_util.tree_map(
                lambda l: jnp.full(l.shape, 0.25 * (t + 1)), params)
            u, s = opt.update(g, s, p)
            p = optax.apply_updates(p, u)
        return p

    ps, pr = run(True), run(False)
    for k in ps:
        np.testing.assert_allclose(np.asarray(ps[k]), np.asarray(pr[k]),
                                   rtol=1e-6, err_msg=k)


def test_sharded_state_specs(hvd):
    """Flat padded buffers ride P('hvd'); scalar bookkeeping (adam's
    count) stays replicated P()."""
    params = _params()
    opt = hj.DistributedOptimizer(optax.adam(1e-3), sharded_update=True)
    state = opt.init(params)
    specs = hj.sharded_state_specs(state)
    leaves = jax.tree_util.tree_leaves(
        state, is_leaf=lambda x: isinstance(x, jnp.ndarray))
    spec_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P))
    assert len(leaves) == len(spec_leaves)
    n = hvd.size()
    for leaf, spec in zip(leaves, spec_leaves):
        if jnp.ndim(leaf) >= 1:
            assert leaf.shape[0] % n == 0, "buffers must pad to N"
            assert spec == P("hvd"), (leaf.shape, spec)
        else:
            assert spec == P(), (leaf.shape, spec)


def test_sharded_update_init_pads_to_world_multiple(hvd):
    """init()'s per-dtype buffers are zero-padded to a world-size
    multiple — 33 f32 elements become 40 on the 8-device mesh."""
    params = _params()
    sharded = hj.shard_update(optax.sgd(0.1, momentum=0.9))
    state = sharded.init(params)
    bufs = [l for l in jax.tree_util.tree_leaves(state) if jnp.ndim(l) == 1]
    assert bufs and all(b.shape[0] == 40 for b in bufs), [
        b.shape for b in bufs]


def test_sharded_update_rejects_accumulation(hvd):
    """sharded_update's flat-buffer state cannot be told apart from the
    accumulation wrapper's param-structured accumulators by
    sharded_state_specs — the combination must refuse loudly instead of
    silently sharding an accumulator."""
    with pytest.raises(ValueError, match="backward_passes_per_step"):
        hj.DistributedOptimizer(optax.sgd(0.1), sharded_update=True,
                                backward_passes_per_step=2)


def test_accumulation_skip_returns_cached_zero_tree(hvd):
    """The non-boundary microstep must not materialize a fresh
    param-sized zero tree: the skip path returns the SAME cached
    buffers on every call (the updates contract promises values, not
    fresh arrays), and the boundary update is unchanged."""
    params = {"w": jnp.ones((5,)), "b": jnp.zeros(())}
    opt = hj.DistributedOptimizer(optax.sgd(0.1),
                                  backward_passes_per_step=3)
    state = opt.init(params)
    g = {"w": jnp.ones((5,)), "b": jnp.ones(())}
    u1, state = opt.update(g, state, params)
    u2, state = opt.update(g, state, params)
    for a, b in zip(jax.tree_util.tree_leaves(u1),
                    jax.tree_util.tree_leaves(u2)):
        assert a is b, "skip path must reuse one zero tree"
        np.testing.assert_array_equal(np.asarray(a), np.zeros(a.shape))
    u3, state = opt.update(g, state, params)
    np.testing.assert_allclose(np.asarray(u3["w"]), -0.1 * np.ones(5),
                               rtol=1e-6)


def test_world_size_one_elides_collectives(hvd):
    """A 1-rank world compiles the DistributedOptimizer step with NO
    all-reduce and NO pack/unpack concatenate — the r5 one-chip bench
    paid a full extra HBM round trip of the gradient tree for an
    identity reduction (docs/benchmarks.md 'HBM diet'). Subprocess: the
    suite's own world is 8 virtual devices."""
    code = """
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp, numpy as np, optax
import horovod_tpu as hvd, horovod_tpu.jax as hj
from jax.sharding import PartitionSpec as P
hvd.init()
assert hvd.size() == 1, hvd.size()
x = jnp.arange(8.0)
np.testing.assert_array_equal(np.asarray(hvd.allreduce(x)), np.asarray(x))
np.testing.assert_array_equal(np.asarray(hvd.broadcast(x, 0)), np.asarray(x))
np.testing.assert_array_equal(np.asarray(hvd.reducescatter(x)), np.asarray(x))
# No lossy wire cast either: bf16 compression short-circuits at size 1.
y = jnp.float32(1.0) + jnp.float32(1e-4)
np.testing.assert_array_equal(
    np.asarray(hj.allreduce(y[None], compression=hj.Compression.bf16)),
    np.asarray(y[None]))
opt = hj.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                              fused_update=True)
params = {"a": jnp.ones((64, 64)), "b": jnp.ones((7,))}
s = opt.init(params)
def step(p, s, g):
    u, s2 = opt.update(g, s, p)
    return optax.apply_updates(p, u), s2
f = hj.jit(step, in_specs=(P(), P(), P()), out_specs=(P(), P()))
txt = f.lower(params, s, params).compile().as_text()
assert "all-reduce" not in txt, "size-1 allreduce must be elided"
assert "concatenate" not in txt, "size-1 grouped pack must be elided"
print("ELIDED-OK")
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # Strip the 8-device flag the suite's conftest forces: this world
    # must see exactly one device.
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", code], env=env, cwd=repo,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "ELIDED-OK" in proc.stdout
