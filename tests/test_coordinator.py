"""Unit tests for the cross-controller negotiation protocol
(horovod_tpu/core/coordinator.py) using the in-memory LocalKV: N
coordinator instances on N threads stand in for N controller processes.

Mirrors the guarantees of the reference's rank-0 coordinator
(reference: horovod/common/operations.cc:279-517): readiness requires
every process, mismatched requests surface the SAME error on every
process, fusion composition is agreed, and stalls are attributed to the
processes that have not submitted."""

import logging
import threading
import time

import pytest

from horovod_tpu.core.coordinator import (
    Coordinator,
    Decision,
    Group,
    KVError,
    LocalKV,
    NegotiationTimeout,
    PeerShutdown,
    RequestMeta,
    decide,
)


def meta(name, op="allreduce", dtype="float32", shape=(4,), **kw):
    import numpy as np

    nbytes = int(np.prod(shape)) * 4
    return RequestMeta(name=name, op=op, dtype=dtype, itemsize=4,
                       shape=tuple(shape), nbytes=nbytes, **kw)


def run_round(per_process_entries, nproc=2, fusion=1 << 26, **coord_kw):
    """Run one negotiation round on nproc threads; return decisions."""
    store = {}
    results = [None] * nproc
    errors = [None] * nproc
    timeout_s = coord_kw.pop("timeout_s", 10.0)

    def worker(pid):
        c = Coordinator(LocalKV(store), nproc, pid, 0.005, fusion,
                        timeout_s=timeout_s, **coord_kw)
        try:
            results[pid] = c.negotiate(per_process_entries[pid])
        except Exception as exc:  # surfaced to the test
            errors[pid] = exc

    threads = [threading.Thread(target=worker, args=(p,))
               for p in range(nproc)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    return results, errors


class TestDecide:
    def test_ready_requires_all_processes(self):
        a = [meta("x"), meta("y")]
        b = [meta("x")]
        groups = decide({0: a, 1: b}, a, fusion_threshold=1 << 20)
        executed = [i for g in groups for i in g.indices]
        assert executed == [0]  # only 'x'; 'y' stays pending

    def test_lexicographic_order_and_fusion(self):
        a = [meta("b"), meta("a"), meta("c", dtype="float64")]
        groups = decide({0: a, 1: a}, a, fusion_threshold=1 << 20)
        # a+b fuse (same dtype); c is its own group.
        assert [g.indices for g in groups] == [[1, 0], [2]]
        assert all(g.error is None for g in groups)

    def test_fusion_threshold_splits_groups(self):
        a = [meta("a", shape=(4,)), meta("b", shape=(4,))]
        groups = decide({0: a, 1: a}, a, fusion_threshold=16)
        assert [g.indices for g in groups] == [[0], [1]]

    def test_zero_threshold_disables_fusion(self):
        a = [meta("a"), meta("b")]
        groups = decide({0: a, 1: a}, a, fusion_threshold=0)
        assert [g.indices for g in groups] == [[0], [1]]

    def test_mismatched_dtype_is_error_group(self):
        a = [meta("x", dtype="float32")]
        b = [meta("x", dtype="float64", )]
        for mine, table in ((a, {0: a, 1: b}), (b, {0: a, 1: b})):
            groups = decide(table, mine, fusion_threshold=1 << 20)
            assert len(groups) == 1 and groups[0].error
            assert "Mismatched data types" in groups[0].error

    def test_mismatched_shape_and_root(self):
        a = [meta("x", shape=(2, 3))]
        b = [meta("x", shape=(4,))]
        groups = decide({0: a, 1: b}, a, fusion_threshold=0)
        assert "Mismatched tensor shapes" in groups[0].error

        a = [meta("r", op="broadcast", root_rank=0)]
        b = [meta("r", op="broadcast", root_rank=1)]
        groups = decide({0: a, 1: b}, a, fusion_threshold=0)
        assert "Mismatched root ranks" in groups[0].error

    def test_mismatch_message_names_the_differing_process(self):
        # With 3 processes, the error must name the process that actually
        # disagrees (and the right field), not the first two.
        a = [meta("x", dtype="float32")]
        c = [meta("x", dtype="float64")]
        groups = decide({0: a, 1: a, 2: c}, a, fusion_threshold=0)
        assert "Mismatched data types" in groups[0].error
        assert "process 2" in groups[0].error

    def test_mismatched_dcn_wire_policy_fails_fast_by_name(self):
        # One side would quantize the cross-tier shard, the other would
        # not — the error must name the per-tier knob, not just "shapes".
        a = [meta("x", compression_dcn="int8")]
        b = [meta("x")]
        for mine in (a, b):
            groups = decide({0: a, 1: b}, mine, fusion_threshold=0)
            assert len(groups) == 1 and groups[0].error
            assert "DCN-tier wire policies" in groups[0].error
            assert "HVD_COMPRESSION_DCN" in groups[0].error

    def test_dcn_wire_policy_splits_fusion_groups(self):
        # Same dtype, different per-tier policy: fusing them would run
        # one batch under one executor wire setting — they must not fuse.
        a = [meta("a", compression_dcn="int8"), meta("b"),
             meta("c", compression_dcn="int8")]
        groups = decide({0: a, 1: a}, a, fusion_threshold=1 << 20)
        assert [g.indices for g in groups] == [[0, 2], [1]]
        assert all(g.error is None for g in groups)

    def test_wire_roundtrip_preserves_dcn_policy(self):
        m = meta("x", compression_dcn="int8")
        m2 = RequestMeta.from_wire(m.wire())
        assert m2.compression_dcn == "int8"
        assert m2 == m
        # Back-compat: a pre-per-tier peer's 11-element row defaults it.
        legacy = RequestMeta.from_wire(meta("x").wire()[:11])
        assert legacy.compression_dcn == "none"

    def test_allgather_first_dim_may_differ(self):
        a = [meta("g", op="allgather", shape=(2, 3))]
        b = [meta("g", op="allgather", shape=(5, 3))]
        groups = decide({0: a, 1: b}, a, fusion_threshold=1 << 20)
        assert groups[0].error is None

        b2 = [meta("g", op="allgather", shape=(5, 4))]
        groups = decide({0: a, 1: b2}, a, fusion_threshold=1 << 20)
        assert "Mismatched tensor shapes" in groups[0].error

    def test_identical_decision_on_every_process(self):
        a = [meta("m"), meta("k"), meta("z", op="broadcast")]
        b = [meta("k"), meta("z", op="broadcast"), meta("m")]
        ga = decide({0: a, 1: b}, a, fusion_threshold=1 << 20)
        gb = decide({0: a, 1: b}, b, fusion_threshold=1 << 20)
        names_a = [[a[i].name for i in g.indices] for g in ga]
        names_b = [[b[i].name for i in g.indices] for g in gb]
        assert names_a == names_b  # same composition, same order


class TestRounds:
    def test_two_process_round_agrees(self):
        e = [meta("a"), meta("b")]
        results, errors = run_round({0: e, 1: e})
        assert errors == [None, None]
        for r in results:
            assert isinstance(r, Decision)
            assert [g.indices for g in r.groups] == [[0, 1]]

    def test_params_flow_from_process_zero(self):
        store = {}
        decisions = {}

        def worker(pid, cycle, fusion):
            c = Coordinator(LocalKV(store), 2, pid, cycle, fusion,
                            timeout_s=10.0)
            decisions[pid] = c.negotiate([])

        t0 = threading.Thread(target=worker, args=(0, 0.042, 12345))
        t1 = threading.Thread(target=worker, args=(1, 0.005, 999))
        t0.start(), t1.start()
        t0.join(10), t1.join(10)
        # Process 1 adopted process 0's params.
        assert decisions[1].cycle_time_s == 0.042
        assert decisions[1].fusion_threshold == 12345

    def test_timeout_names_the_laggard(self):
        store = {}
        c = Coordinator(LocalKV(store), 2, 0, 0.005, 0, timeout_s=0.7)
        with pytest.raises(NegotiationTimeout) as ei:
            c.negotiate([meta("x")])
        assert "process 1" in str(ei.value)
        assert c.dead  # poisoned afterwards

    def test_peer_shutdown_tombstone(self):
        store = {}
        dead = Coordinator(LocalKV(store), 2, 1, 0.005, 0)
        dead.close()
        c = Coordinator(LocalKV(store), 2, 0, 0.005, 0, timeout_s=5.0)
        with pytest.raises(PeerShutdown):
            c.negotiate([meta("x")])

    def test_prior_generation_residue_reclaimed(self):
        """A closed generation's leftover keys (final rounds + tombstone)
        are deleted once the NEXT generation completes its first round —
        proof every peer moved on (bounded KV usage across engine
        init/shutdown generations)."""
        from horovod_tpu.core import coordinator as coord

        store = {}
        old = [Coordinator(LocalKV(store), 2, p, 0.001, 0, timeout_s=5.0,
                           namespace="hvd/neg/gen-old") for p in (0, 1)]
        results, errors = {}, {}

        def round_of(cs, pid):
            try:
                results[(cs[pid].ns, pid)] = cs[pid].negotiate([])
            except Exception as exc:  # pragma: no cover - surfaced below
                errors[(cs[pid].ns, pid)] = exc

        ts = [threading.Thread(target=round_of, args=(old, p)) for p in (0, 1)]
        [t.start() for t in ts]
        [t.join(5) for t in ts]
        for c in old:
            c.close()
        assert not errors
        assert any("gen-old" in k for k in store if isinstance(k, str))

        new = [Coordinator(LocalKV(store), 2, p, 0.001, 0, timeout_s=5.0,
                           namespace="hvd/neg/gen-new") for p in (0, 1)]
        ts = [threading.Thread(target=round_of, args=(new, p)) for p in (0, 1)]
        [t.start() for t in ts]
        [t.join(5) for t in ts]
        assert not errors
        leftover = [k for k in store
                    if isinstance(k, str) and "gen-old" in k]
        assert not leftover, leftover
        with coord._residue_lock:
            assert not any(ns == "hvd/neg/gen-old"
                           for ns, _ in coord._residue)

    def test_key_cleanup_after_rounds(self):
        store = {}
        results = [None, None]

        def worker(pid):
            c = Coordinator(LocalKV(store), 2, pid, 0.001, 0, timeout_s=10.0)
            for _ in range(4):
                results[pid] = c.negotiate([])

        ts = [threading.Thread(target=worker, args=(p,)) for p in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(10)
        round_keys = [k for k in store if "/r" in str(k)]
        # Rounds 0..3 ran; only the last two rounds' keys may linger.
        assert all("/r2/" in k or "/r3/" in k for k in round_keys), store

    def test_idle_backoff_grows(self):
        e = []
        results, errors = run_round({0: e, 1: e})
        assert errors == [None, None]
        assert all(r.idle_backoff_s > 0 for r in results)

    def test_stall_attribution_warning(self, caplog):
        stale = [meta("slowpoke", age_s=99.0)]
        with caplog.at_level(logging.WARNING,
                             logger="horovod_tpu.coordinator"):
            # Process 1 never announces 'slowpoke'.
            results, errors = run_round({0: stale, 1: []}, nproc=2,
                                        stall_warning_s=1.0)
        assert errors == [None, None]
        msgs = [r.getMessage() for r in caplog.records]
        assert any("slowpoke" in m and "process(es): 1" in m for m in msgs)

    def test_stall_warning_names_counter_divergence(self, caplog):
        """When a peer holds the SAME collective under a different
        sequence number (asymmetric retrace marched its construction
        counter forward), the stall warning must name the divergence —
        this stall can never resolve, unlike an ordinary straggler
        (r4 advisor finding on the TF bridge's process-global counter)."""
        mine = [meta("tf.allreduce.g3.w", age_s=99.0)]
        theirs = [meta("tf.allreduce.g4.w", age_s=99.0)]
        with caplog.at_level(logging.WARNING,
                             logger="horovod_tpu.coordinator"):
            results, errors = run_round({0: mine, 1: theirs}, nproc=2,
                                        stall_warning_s=1.0)
        assert errors == [None, None]
        msgs = [r.getMessage() for r in caplog.records]
        assert any("tf.allreduce.g4.w" in m and "sequence number" in m
                   for m in msgs), msgs
        # Only the LOWER-holding process diagnoses divergence; the peer
        # holding the higher name sees a plain straggler (a peer behind
        # on lower numbers may simply catch up — no false positives for
        # ordinary async stragglers).
        assert sum("sequence number" in m for m in msgs) == 1, msgs
        # An ordinary straggler (no same-skeleton peer name) must NOT
        # carry the divergence hint.
        caplog.clear()
        with caplog.at_level(logging.WARNING,
                             logger="horovod_tpu.coordinator"):
            run_round({0: [meta("plain", age_s=99.0)], 1: []}, nproc=2,
                      stall_warning_s=1.0)
        msgs = [r.getMessage() for r in caplog.records]
        assert any("plain" in m for m in msgs)
        assert not any("sequence number" in m for m in msgs)


class TestAggregatedRounds:
    """HVD_NEGOTIATION_AGGREGATE=1 — the gather-tree round shape
    (reference: rank-0 MPI_Gatherv + response broadcast,
    operations.cc:2117-2131): p0 reads P-1 peers and republishes ONE
    digest; peers read only that. Decisions must be bit-identical to
    the symmetric protocol's."""

    def _world(self, per_process_entries, nproc, monkeypatch, fusion=1 << 26):
        monkeypatch.setenv("HVD_NEGOTIATION_AGGREGATE", "1")
        store = {}
        results = [None] * nproc
        errors = [None] * nproc
        coords = [None] * nproc

        def worker(pid):
            c = Coordinator(LocalKV(store), nproc, pid, 0.005, fusion,
                            timeout_s=10.0)
            coords[pid] = c
            assert c.aggregate
            try:
                results[pid] = c.negotiate(per_process_entries[pid])
            except Exception as exc:
                errors[pid] = exc

        threads = [threading.Thread(target=worker, args=(p,))
                   for p in range(nproc)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert errors == [None] * nproc, errors
        return results, coords, store

    def test_same_decision_as_symmetric(self, monkeypatch):
        entries = [[meta("a"), meta("b")], [meta("b"), meta("a")],
                   [meta("a"), meta("b")], [meta("b")]]
        agg, _, _ = self._world(entries, 4, monkeypatch)
        monkeypatch.delenv("HVD_NEGOTIATION_AGGREGATE")
        sym, errs = run_round(entries, nproc=4)
        assert errs == [None] * 4
        for a, s in zip(agg, sym):
            assert [g.indices for g in a.groups] == \
                   [g.indices for g in s.groups]
            assert (a.cycle_time_s, a.fusion_threshold) == \
                   (s.cycle_time_s, s.fusion_threshold)

    def test_non_roots_read_one_key_per_round(self, monkeypatch):
        entries = [[meta("x")] for _ in range(4)]
        _, coords, _ = self._world(entries, 4, monkeypatch)
        assert coords[0].stats["kv_gets"] == 3  # p0 gathers P-1 peers
        for c in coords[1:]:
            assert c.stats["kv_gets"] == 1, c.stats  # ONE digest read

    def test_stall_attribution_survives_digest(self, monkeypatch):
        # Everyone announced "x"; only p0 announced "lag" — every
        # process must name the processes missing it, incl. digest
        # readers (reference: CheckForStalledTensors names ranks).
        entries = [[meta("x"), meta("lag")]] + [[meta("x")]] * 3
        _, coords, _ = self._world(entries, 4, monkeypatch)
        for c in coords:
            assert c.missing_processes("lag") == [1, 2, 3]

    def test_digest_keys_cleaned_up(self, monkeypatch):
        monkeypatch.setenv("HVD_NEGOTIATION_AGGREGATE", "1")
        store = {}
        coords = [Coordinator(LocalKV(store), 2, p, 0.005, 0,
                              timeout_s=10.0) for p in range(2)]

        def rounds(c, n):
            for _ in range(n):
                c.negotiate([meta("t")])

        threads = [threading.Thread(target=rounds, args=(c, 3))
                   for c in coords]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        digests = [k for k in store if k.endswith("/all")]
        # Only the live round's digest (and possibly the just-written
        # next one) may remain — consumed rounds are reclaimed.
        assert len(digests) <= 2, sorted(store)

    def test_straggler_attribution_reaches_digest_readers(self, monkeypatch):
        """P=3 gather-tree, process 2 never publishes: p0 times out
        naming process 2, and process 1 — which can only see p0's
        digest — must receive THAT attribution (the error digest), not
        a generic 'process 0 timed out' (code-review r4 finding)."""
        monkeypatch.setenv("HVD_NEGOTIATION_AGGREGATE", "1")
        store = {}
        errors = {}

        def worker(pid):
            c = Coordinator(LocalKV(store), 3, pid, 0.005, 0,
                            timeout_s=1.0)
            try:
                c.negotiate([meta("t")])
            except Exception as exc:
                errors[pid] = exc

        threads = [threading.Thread(target=worker, args=(p,))
                   for p in (0, 1)]  # process 2 is the silent straggler
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert isinstance(errors.get(0), NegotiationTimeout)
        assert errors[0].process == 2
        assert "process 2" in str(errors.get(1)), errors

    def test_mixed_mode_fails_fast(self):
        """HVD_NEGOTIATION_AGGREGATE set on only SOME processes used to
        deadlock until the full negotiation timeout — each side waits on
        a key the other mode never writes. The mismatch must be named
        within a poll slice instead (r4 advisor)."""
        for agg0 in (False, True):
            store = {}
            errors = {}

            def worker(pid, agg):
                c = Coordinator(LocalKV(store), 2, pid, 0.005, 0,
                                timeout_s=8.0)
                c.aggregate = agg  # env is process-global; set directly
                try:
                    c.negotiate([meta("x")])
                except Exception as exc:
                    errors[pid] = exc

            t0 = time.monotonic()
            threads = [
                threading.Thread(target=worker, args=(0, agg0)),
                threading.Thread(target=worker, args=(1, not agg0)),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            elapsed = time.monotonic() - t0
            mismatches = [e for e in errors.values()
                          if isinstance(e, KVError)
                          and "AGGREGATE mismatch" in str(e)]
            assert mismatches, (agg0, errors)
            # Fail-FAST: well under the 8 s negotiation timeout.
            assert elapsed < 6.0, elapsed
