"""Timeline parity between the C++ and Python writers, and the XLA
profile-capture harness (reference: common/timeline.cc detail — dtype and
shape args on events — and the CUDA-event device timing that the XLA
profiler replaces, operations.cc:671-695)."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest


def _run_ops(engine):
    # Synchronize after each enqueue: one entry per engine cycle, so the
    # event stream is deterministic (whether same-cycle allreduces fuse
    # depends on enqueue/drain timing; fusion-path events are covered by
    # the multi-process engine_fusion scenario).
    engine.synchronize(
        engine.allreduce_async("t/a", np.ones((4,), np.float32), False))
    engine.synchronize(
        engine.allreduce_async("t/b", np.ones((4,), np.float32), False))
    engine.synchronize(
        engine.allgather_async("t/g", np.ones((2, 3), np.float32)))
    engine.synchronize(
        engine.broadcast_async("t/c", np.ones((5,), np.float32), 0))
    engine.shutdown()


def _summarize(path):
    """Per-tensor set of (activity, phase, args) — the diff-comparable
    shape of a timeline, timestamps excluded."""
    lanes = {}
    events = {}
    for ev in json.load(open(path)):
        if not ev:
            continue
        if ev.get("ph") == "M":
            # Structural metadata: lane names feed the summary; the
            # HVD_CLOCK record (distributed tracing) is not a span.
            if ev.get("name") == "process_name":
                lanes[ev["pid"]] = ev["args"]["name"]
            continue
        pid = ev.get("pid")
        args = ev.get("args")
        events.setdefault(pid, set()).add(
            (ev["name"], ev["ph"],
             None if args is None else (args.get("dtype"),
                                        tuple(args.get("shape", ())))))
    return {lanes[pid]: evs for pid, evs in events.items()}


def test_cpp_timeline_diff_comparable_with_python_twin(hvd, tmp_path):
    from horovod_tpu.core import timeline as tl
    from horovod_tpu.core.engine import Engine
    from horovod_tpu.core.native_engine import NativeEngine
    from horovod_tpu.core.timeline import Timeline

    cpp_path = str(tmp_path / "cpp.json")
    py_path = str(tmp_path / "py.json")
    _run_ops(NativeEngine(timeline_path=cpp_path))
    _run_ops(Engine(timeline=Timeline(py_path)))

    cpp, py = _summarize(cpp_path), _summarize(py_path)
    assert set(cpp) == set(py) == {"t/a", "t/b", "t/g", "t/c"}
    for name in cpp:
        # Same activities with the same phase types and the same
        # dtype/shape args on collective begins.
        assert cpp[name] == py[name], (name, cpp[name] ^ py[name])
    # Spot-check the detail the reference writer records
    # (timeline.cc:98-188): dtype + shape on the collective begin event.
    assert ("ALLGATHER", "B", ("float32", (2, 3))) in cpp["t/g"]
    # Both writers must cover the single-op vocabulary declared in
    # core/timeline.py — not merely agree with each other (the reference
    # emits WAIT_FOR_DATA before every executed op, operations.cc:783-807;
    # MEMCPY is the submit-time snapshot span of the zero-copy data
    # plane, nested at the head of QUEUE).
    for summary in (cpp, py):
        acts = {a for evs in summary.values() for a, _, _ in evs}
        assert acts == {tl.QUEUE, tl.MEMCPY, tl.WAIT_FOR_DATA,
                        tl.ALLREDUCE, tl.ALLGATHER, tl.BROADCAST}, acts


class _PluggedExecutor:
    """Echo executor whose FIRST call blocks until release(), so tensors
    enqueued meanwhile pile up in the queue and fuse on the next drain —
    a deterministic way to drive the fusion-buffer timeline path."""

    def __init__(self):
        import threading

        self.gate = threading.Event()
        self.started = threading.Event()
        self.calls = 0

    def allreduce(self, flat, average):
        self.calls += 1
        if self.calls == 1:
            self.started.set()
            self.gate.wait(5.0)
        return flat.copy()


def _run_fused(engine, ex):
    h0 = engine.allreduce_async("t/plug", np.ones((2,), np.float32), False)
    # Only once the plug is INSIDE the executor is the dispatch thread
    # provably busy; tensors enqueued now stack up and fuse next cycle.
    assert ex.started.wait(5.0)
    ha = engine.allreduce_async("t/fa", np.ones((4,), np.float32), False)
    hb = engine.allreduce_async("t/fb", np.ones((4,), np.float32), False)
    ex.gate.set()
    for h in (h0, ha, hb):
        engine.synchronize(h)
    engine.shutdown()


@pytest.mark.parametrize("impl", ["native", "python"])
def test_fused_timeline_covers_declared_vocabulary(hvd, tmp_path, impl):
    """Every activity constant declared in core/timeline.py is actually
    emitted by both writers (VERDICT r2 weak #5: WAIT_FOR_DATA and
    MEMCPY_OUT_FUSION_BUFFER were declared but never written; reference
    emits out-copy spans, operations.cc:1359-1374). NEGOTIATE_* phases are
    multi-controller-only and covered by tests/multiproc_worker.py."""
    from horovod_tpu.core import timeline as tl
    from horovod_tpu.core.engine import Engine
    from horovod_tpu.core.native_engine import NativeEngine
    from horovod_tpu.core.timeline import Timeline

    path = str(tmp_path / f"{impl}.json")
    ex = _PluggedExecutor()
    if impl == "native":
        engine = NativeEngine(executor=ex, timeline_path=path)
    else:
        engine = Engine(executor=ex, timeline=Timeline(path))
    _run_fused(engine, ex)

    summary = _summarize(path)
    acts = {a for evs in summary.values() for a, _, _ in evs}
    declared = {tl.QUEUE, tl.MEMCPY, tl.WAIT_FOR_DATA,
                tl.MEMCPY_IN_FUSION_BUFFER, tl.ALLREDUCE,
                tl.MEMCPY_OUT_FUSION_BUFFER}
    assert acts == declared, acts ^ declared
    # The fused tensors carry the fusion-buffer spans; the plug ran alone.
    for name in ("t/fa", "t/fb"):
        lane_acts = {a for a, _, _ in summary[name]}
        assert tl.MEMCPY_IN_FUSION_BUFFER in lane_acts, (name, lane_acts)
        assert tl.MEMCPY_OUT_FUSION_BUFFER in lane_acts, (name, lane_acts)
    assert tl.MEMCPY_IN_FUSION_BUFFER not in {
        a for a, _, _ in summary["t/plug"]}


def test_timeline_truncation_safe(hvd, tmp_path):
    """Crash-safety (ISSUE 2 satellite): a killed run leaves no closing
    ']' — the writer's separator-first style must leave no trailing comma
    either, so the file still loads after appending the bracket (what
    Perfetto's tolerant JSON-array reader does). Both writers."""
    from horovod_tpu.core.engine import Engine
    from horovod_tpu.core.native_engine import NativeEngine
    from horovod_tpu.core.timeline import Timeline

    py_path = str(tmp_path / "py_trunc.json")
    t = Timeline(py_path)
    t.start("t/x", "QUEUE")
    t.end("t/x", "QUEUE")
    t._fh.flush()
    # Simulate SIGKILL: read the file WITHOUT close().
    raw = open(py_path).read()
    assert not raw.rstrip().endswith(",")
    events = json.loads(raw + "]")
    assert any(ev.get("name") == "QUEUE" for ev in events)
    t.close()  # idempotent clean close still yields valid JSON
    events = json.load(open(py_path))
    assert any(ev.get("name") == "QUEUE" for ev in events)
    t.close()  # second close is a no-op

    # The C++ writer flushes on its 1 s horizon at event boundaries, so a
    # mid-run snapshot (the SIGKILL view) is a complete-event prefix with
    # no trailing comma and no ']'.
    import time

    cpp_path = str(tmp_path / "cpp_trunc.json")
    e = NativeEngine(timeline_path=cpp_path)
    try:
        e.synchronize(
            e.allreduce_async("t/c0", np.ones((4,), np.float32), False))
        time.sleep(1.2)  # cross the flush horizon on the next emit
        e.synchronize(
            e.allreduce_async("t/c1", np.ones((4,), np.float32), False))
        raw = open(cpp_path).read()
        assert raw.strip() != "[", "flush horizon not crossed"
        assert not raw.rstrip().endswith(",")
        assert json.loads(raw + "]")  # loadable after truncation
    finally:
        e.shutdown()
    events = json.load(open(cpp_path))
    assert any(ev.get("name") == "QUEUE" for ev in events)

    # Python Engine.shutdown closes the timeline it owns (no leak).
    leak_path = str(tmp_path / "owned.json")
    eng = Engine(timeline=Timeline(leak_path))
    eng.synchronize(
        eng.allreduce_async("t/p", np.ones((2,), np.float32), False))
    eng.shutdown()
    assert json.load(open(leak_path))


def test_profiler_capture_produces_trace(hvd, tmp_path):
    import jax

    from horovod_tpu.utils import profiler

    logdir = str(tmp_path / "prof")

    @jax.jit
    def step(x):
        return (x * 2.0).sum()

    out = profiler.capture(step, jnp.ones((8, 8)), logdir=logdir, iters=2)
    files = profiler.trace_files(out)
    assert files, f"no xplane files under {logdir}: {os.listdir(logdir)}"


def _synthetic_xspace(tmp_path):
    """A hand-built device plane exercising every xplane metric: two
    compute fusions (one HBM-direct, one VMEM-only), an async copy pair,
    a while wrapper, an XLA Modules span, plus one collective and one
    optimizer-update fusion for the per-op-class attribution."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    space = xplane_pb2.XSpace()
    plane = space.planes.add(name="/device:TPU:0")
    names = {
        1: "%convert_reduce_fusion.7 = bf16[8,128]{1,0:T(8,128)} fusion("
           "bf16[8,128]{1,0:T(8,128)} %p0, f32[128]{0:T(128)S(1)} %p1)",
        2: "%fusion.9 = f32[64]{0:T(128)S(1)} fusion(f32[64]{0:T(128)S(1)} %x)",
        3: "%copy-start = (f32[256]{0:T(128)S(1)}, f32[256]{0:T(128)}, u32[]{:S(2)})"
           " copy-start(f32[256]{0:T(128)} %w)",
        4: "%copy-done = f32[256]{0:T(128)S(1)} copy-done(%copy-start)",
        5: "%while.2 = (s32[]{:T(128)}, f32[999999]{0:T(128)}) while(...)",
        6: "jit_step(123)",
        7: "%all-reduce.3 = f32[128]{0:T(128)} all-reduce("
           "f32[128]{0:T(128)} %x)",
        8: "%multiply_add_fusion.11 = f32[256]{0:T(128)} fusion("
           "f32[256]{0:T(128)} %g, f32[256]{0:T(128)S(1)} %m)",
    }
    for i, n in names.items():
        plane.event_metadata[i].id = i
        plane.event_metadata[i].name = n
    ops = plane.lines.add(name="XLA Ops")
    for mid, dur_ps in [(1, 4e9), (2, 1e9), (4, 2e9), (5, 8e9),
                        (7, 2e9), (8, 1.5e9)]:
        ev = ops.events.add(metadata_id=int(mid))
        ev.duration_ps = int(dur_ps)
    async_line = plane.lines.add(name="Async XLA Ops")
    ev = async_line.events.add(metadata_id=3)
    ev.duration_ps = int(3e9)
    mods = plane.lines.add(name="XLA Modules")
    ev = mods.events.add(metadata_id=6)
    ev.duration_ps = int(9e9)
    path = tmp_path / "host.xplane.pb"
    path.write_bytes(space.SerializeToString())
    return str(tmp_path)


def test_xplane_hbm_accounting_on_synthetic_capture(tmp_path):
    """Pins the measured-roofline machinery (docs/benchmarks.md r4): DMA
    payload = destination shape of async copies; fusion direct bytes
    exclude S(n)-annotated (VMEM/SMEM) operands; while wrappers are
    excluded; module time sums the Modules line."""
    from horovod_tpu.utils import xplane as xp

    logdir = _synthetic_xspace(tmp_path)
    d = xp.dma_bytes(logdir)
    assert d["bytes"] == 256 * 4 and d["events"] == 1  # dest f32[256]
    assert d["busy_ms"] == pytest.approx(3.0)
    assert xp.module_ms(logdir) == pytest.approx(9.0)

    # fusion.7: bf16 out 8*128*2 + bf16 operand 8*128*2 (the S(1) f32
    # operand excluded); fusion.9 all-VMEM -> 0; copy-done + while
    # skipped; all-reduce.3 in+out 2*128*4; multiply_add_fusion.11 out +
    # one HBM operand 2*256*4 (the S(1) momentum operand excluded).
    hb = xp.hbm_bytes(logdir)
    assert hb["bytes"] == 2 * (8 * 128 * 2) + 2 * 128 * 4 + 2 * 256 * 4

    report = xp.hbm_report(logdir, steps=1)
    assert "conv+BN fusion" in report and "while" not in report
    assert "true HBM traffic" in report
    assert "per-op-class" in report
    # Per-dtype columns in the human table, heaviest dtype first (f32
    # carries 2*128*4 + 2*256*4 = 3072 B vs bf16's 2*8*128*2 = 4096 B
    # -> bf16 leads).
    header = next(ln for ln in report.splitlines()
                  if ln.strip().startswith("class"))
    assert "GB bf16" in header and "GB f32" in header
    assert header.index("GB bf16") < header.index("GB f32")

    # Per-op-class attribution (collective vs optimizer vs conv/matmul
    # bytes): the table that makes a traffic regression attributable.
    classes = xp.class_breakdown(logdir, steps=1)
    assert classes["collective"]["bytes"] == 2 * 128 * 4
    assert classes["collective"]["ms"] == pytest.approx(2.0)
    assert classes["optimizer"]["bytes"] == 2 * 256 * 4
    assert classes["optimizer"]["ms"] == pytest.approx(1.5)
    assert classes["conv/matmul"]["bytes"] == 2 * (8 * 128 * 2)
    # control (while + copy-done) carries time but never bytes.
    assert classes["control"]["bytes"] == 0
    assert classes["control"]["ms"] == pytest.approx(10.0)
    assert classes["elementwise fusion"]["bytes"] == 0
    # Per-dtype split inside each class (HBM diet round 2): the
    # bf16-vs-f32 audit — fusion.7 streams bf16 in+out, the collective
    # and the optimizer fusion are all-f32 here.
    assert classes["conv/matmul"]["by_dtype"] == {"bf16": 2 * (8 * 128 * 2)}
    assert classes["collective"]["by_dtype"] == {"f32": 2 * 128 * 4}
    assert classes["optimizer"]["by_dtype"] == {"f32": 2 * 256 * 4}
    assert classes["control"]["by_dtype"] == {}
    # steps divides evenly into per-step figures.
    half = xp.class_breakdown(logdir, steps=2)
    assert half["collective"]["bytes"] == 128 * 4
    assert half["collective"]["by_dtype"] == {"f32": 128 * 4}

    # Machine-readable attribution (ISSUE 2 satellite): --json carries
    # the same numbers as the human table, and the stats CLI consumes a
    # capture dir through the same helper instead of re-parsing text.
    data = xp.hbm_json(logdir, steps=1)
    assert data["classes"]["collective"]["bytes"] == 2 * 128 * 4
    # Capture-wide dtype totals ride the JSON (and perf.jsonl via the
    # sentinel fold): sum of the per-class splits.
    assert data["bytes_by_dtype_per_step"] == {
        "bf16": 2 * (8 * 128 * 2), "f32": 2 * 128 * 4 + 2 * 256 * 4}
    assert data["dma_bytes"] == 256 * 4
    assert data["true_hbm_bytes_per_step"] == \
        data["dma_bytes"] + data["fusion_direct_bytes"]
    assert data["module_ms"] == pytest.approx(9.0)
    import io
    import json as _json
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        xp.main([logdir, "--hbm", "--json"])
    assert _json.loads(buf.getvalue()) == _json.loads(_json.dumps(data))

    from horovod_tpu.utils import stats

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert stats.main([logdir, "--json"]) == 0
    env = _json.loads(buf.getvalue())
    # The unified envelope shape (ISSUE 6 satellite): same schema as the
    # file/live/http sources, xplane figures flattened into samples.
    assert set(env) == {"source", "target", "samples"}
    assert env["source"] == "xplane"
    by_name = {(s["name"], s["labels"].get("class")): s["value"]
               for s in env["samples"]}
    assert by_name[("xplane_dma_bytes", None)] == 256 * 4
    assert by_name[("xplane_class_bytes", "collective")] == 2 * 128 * 4
    # The dtype split flattens into labeled samples too (the stats CLI's
    # bf16-vs-f32 view of a capture).
    by_dt = {(s["name"], s["labels"].get("class"), s["labels"].get("dtype")):
             s["value"] for s in env["samples"]}
    assert by_dt[("xplane_bytes_per_step", None, "bf16")] == 2 * (8 * 128 * 2)
    assert by_dt[("xplane_class_dtype_bytes", "collective", "f32")] == \
        2 * 128 * 4

    # Shape parsing corner cases.
    assert xp._first_shape_bytes("%x = pred[3]{0} y(pred[3] %a)") == 3
    assert xp._first_shape_bytes("no shapes") == 0
    assert xp._hbm_shape_bytes(
        "f32[2,2]{1,0:T(8,128)} f32[4]{0:T(128)S(1)} bf16[8]{0}") == 32
    assert xp._op_root("%get-tuple-element.991 = ...") == "get-tuple-element"
    assert xp._op_root("%while.2 = (...) while(...)") == "while"


def test_membw_plumbing_on_cpu():
    """The bandwidth suite's math and jit plumbing (tiny arrays; the
    bandwidth VALUE is only meaningful on the real chip)."""
    from horovod_tpu.utils import membw

    assert membw._slope_ms({1: 0.10, 2: 0.11, 4: 0.13}) == pytest.approx(10.0)
    # CPU timing noise at toy sizes can produce any slope sign; assert
    # the plumbing (keys, traffic accounting), not the bandwidth value.
    r = membw.measure("copy", array_mb=1, iters=(2, 4), repeats=1)
    assert isinstance(r["gbps"], float) and r["traffic_mb_per_iter"] == 2.0
    r = membw.measure("triad", array_mb=1, iters=(2, 4), repeats=1)
    assert isinstance(r["gbps"], float) and r["traffic_mb_per_iter"] == 3.0
