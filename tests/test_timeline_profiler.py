"""Timeline parity between the C++ and Python writers, and the XLA
profile-capture harness (reference: common/timeline.cc detail — dtype and
shape args on events — and the CUDA-event device timing that the XLA
profiler replaces, operations.cc:671-695)."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest


def _run_ops(engine):
    # Synchronize after each enqueue: one entry per engine cycle, so the
    # event stream is deterministic (whether same-cycle allreduces fuse
    # depends on enqueue/drain timing; fusion-path events are covered by
    # the multi-process engine_fusion scenario).
    engine.synchronize(
        engine.allreduce_async("t/a", np.ones((4,), np.float32), False))
    engine.synchronize(
        engine.allreduce_async("t/b", np.ones((4,), np.float32), False))
    engine.synchronize(
        engine.allgather_async("t/g", np.ones((2, 3), np.float32)))
    engine.synchronize(
        engine.broadcast_async("t/c", np.ones((5,), np.float32), 0))
    engine.shutdown()


def _summarize(path):
    """Per-tensor set of (activity, phase, args) — the diff-comparable
    shape of a timeline, timestamps excluded."""
    lanes = {}
    events = {}
    for ev in json.load(open(path)):
        if not ev:
            continue
        if ev.get("name") == "process_name":
            lanes[ev["pid"]] = ev["args"]["name"]
            continue
        pid = ev.get("pid")
        args = ev.get("args")
        events.setdefault(pid, set()).add(
            (ev["name"], ev["ph"],
             None if args is None else (args.get("dtype"),
                                        tuple(args.get("shape", ())))))
    return {lanes[pid]: evs for pid, evs in events.items()}


def test_cpp_timeline_diff_comparable_with_python_twin(hvd, tmp_path):
    from horovod_tpu.core.engine import Engine
    from horovod_tpu.core.native_engine import NativeEngine
    from horovod_tpu.core.timeline import Timeline

    cpp_path = str(tmp_path / "cpp.json")
    py_path = str(tmp_path / "py.json")
    _run_ops(NativeEngine(timeline_path=cpp_path))
    _run_ops(Engine(timeline=Timeline(py_path)))

    cpp, py = _summarize(cpp_path), _summarize(py_path)
    assert set(cpp) == set(py) == {"t/a", "t/b", "t/g", "t/c"}
    for name in cpp:
        # Same activities with the same phase types and the same
        # dtype/shape args on collective begins.
        assert cpp[name] == py[name], (name, cpp[name] ^ py[name])
    # Spot-check the detail the reference writer records
    # (timeline.cc:98-188): dtype + shape on the collective begin event.
    assert ("ALLGATHER", "B", ("float32", (2, 3))) in cpp["t/g"]


def test_profiler_capture_produces_trace(hvd, tmp_path):
    import jax

    from horovod_tpu.utils import profiler

    logdir = str(tmp_path / "prof")

    @jax.jit
    def step(x):
        return (x * 2.0).sum()

    out = profiler.capture(step, jnp.ones((8, 8)), logdir=logdir, iters=2)
    files = profiler.trace_files(out)
    assert files, f"no xplane files under {logdir}: {os.listdir(logdir)}"
