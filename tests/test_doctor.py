"""Tier-1 tests for the hang doctor (ISSUE 18): per-entry engine
introspection (``Engine.inspect`` / ``hvd_engine_inspect`` — identical
record shape, pinned against ``ENGINE_INSPECT_KEYS``), the
grow-until-count-matches inspect buffer protocol, cross-rank stall
classification over the checked-in two-rank hung-state fixture (every
verdict kind in ``VERDICT_KINDS``), the offline ``stats --doctor``
surfaces, the sentinel ``hang`` verdict, and the kind-scoped flight-dump
rate limit. The live 2-process withheld-submit / dead-peer scenarios
ride tests/test_multiprocess.py."""

import ctypes
import json
import os
import threading
import time

import numpy as np
import pytest

from horovod_tpu.core import doctor
from horovod_tpu.core.engine import ENGINE_INSPECT_KEYS

DATA = os.path.join(os.path.dirname(__file__), "data", "doctor_tworank")


def _load_snaps():
    return [json.load(open(os.path.join(DATA, f"snap.rank{r}.json")))
            for r in (0, 1)]


# ---------------------------------------------------------------------------
# Classification over the checked-in hung-state fixture: EVERY kind
# ---------------------------------------------------------------------------


def test_fixture_pins_every_verdict_kind():
    """Two survivor snapshots out of a 4-rank world (rank 2 silent but
    alive, rank 3 dead) produce all six classification kinds in one
    diagnosis — the full vocabulary stays reachable."""
    verdict = doctor.classify(_load_snaps(), nproc=4,
                              dead={3: "lease expired (sigkill)"})
    kinds = {f["kind"] for f in verdict["findings"]}
    assert kinds == set(doctor.VERDICT_KINDS), kinds
    # Attribution priority: a KNOWN-dead peer outranks everything.
    assert verdict["kind"] == "dead_peer"
    assert verdict["ranks"] == [3]
    assert verdict["ranks_reporting"] == [0, 1]
    assert verdict["nproc"] == 4


def test_fixture_attribution_details():
    verdict = doctor.classify(_load_snaps(), nproc=4,
                              dead={3: "lease expired (sigkill)"})
    by_kind = {}
    for f in verdict["findings"]:
        by_kind.setdefault(f["kind"], []).append(f)
    # missing_submitter names the exact tensor and the exact rank —
    # the silent-but-alive rank 2, never the dead or draining ranks.
    for f in by_kind["missing_submitter"]:
        assert f["ranks"] == [2]
        assert f["tensor"] in ("grad/a", "grad/b")
        assert "never announced" in f["detail"]
    # metadata_mismatch: grad/b skews on (dtype, wire) between 0 and 1.
    (mm,) = by_kind["metadata_mismatch"]
    assert mm["tensor"] == "grad/b" and mm["ranks"] == [0, 1]
    assert "skew" in mm["detail"]
    # dead_peer carries the elastic death note.
    (dp,) = by_kind["dead_peer"]
    assert dp["ranks"] == [3] and "lease expired" in dp["detail"]
    # draining: rank 1 published a drain marker.
    assert any(f["ranks"] == [1] for f in by_kind["draining"])
    # slow_executor: rank 0's grad/a sits in ALLREDUCE 250x its median.
    (slow,) = by_kind["slow_executor"]
    assert slow["tensor"] == "grad/a" and slow["ranks"] == [0]
    # kv_degraded: rank 1 counted 3 failovers.
    (kv,) = by_kind["kv_degraded"]
    assert kv["ranks"] == [1] and "x3" in kv["detail"]


def test_classify_skips_malformed_snapshots_and_empty_world():
    v = doctor.classify([{"junk": True}, {"rank": "NaN"}])
    assert v["kind"] is None and v["findings"] == []
    assert v["ranks_reporting"] == []
    # A healthy world (everyone submitted everything) attributes nothing.
    snaps = _load_snaps()
    healthy = doctor.classify(snaps[:1], nproc=1)
    assert all(f["kind"] != "missing_submitter"
               for f in healthy["findings"])


def test_classify_newest_snapshot_per_rank_wins():
    old = {"rank": 0, "nproc": 2, "wall": 100.0,
           "entries": [{"name": "stale/t", "op": "allreduce"}]}
    new = {"rank": 0, "nproc": 2, "wall": 200.0, "entries": []}
    peer = {"rank": 1, "nproc": 2, "wall": 200.0, "entries": []}
    v = doctor.classify([old, new, peer])
    # rank0's newer empty table supersedes the stale one: no diff left.
    assert v["kind"] is None, v


# ---------------------------------------------------------------------------
# Offline diagnosis over flight dumps (the `stats --doctor <dir>` path)
# ---------------------------------------------------------------------------


def test_diagnose_dumps_over_checked_in_dumps():
    """The checked-in dump set: rank 0 announced sync/only0, rank 1's
    NEWEST dump did not (its older dump had it — newest per rank wins).
    The offline diff blames the exact tensor and rank, and folds the
    dumped telemetry's KV failovers in."""
    paths = doctor.flight_dump_paths(DATA)
    assert len(paths) == 3  # snap.rank*.json are NOT flight dumps
    v = doctor.diagnose_dumps(paths)
    assert v["kind"] == "missing_submitter"
    assert v["tensor"] == "sync/only0" and v["ranks"] == [1]
    assert any(f["kind"] == "kv_degraded" and f["ranks"] == [1]
               for f in v["findings"])


def test_diagnose_dumps_skips_dumps_without_inspect(tmp_path):
    plain = tmp_path / "hvd_flight.rank0.1.2.json"
    plain.write_text(json.dumps({"rank": 0, "wall_us": 5,
                                 "reason": "shutdown", "events": []}))
    broken = tmp_path / "hvd_flight.rank1.1.3.json"
    broken.write_text("{not json")
    v = doctor.diagnose_dumps([str(plain), str(broken),
                               str(tmp_path / "missing.json")])
    assert v["kind"] is None and v["ranks_reporting"] == []


# ---------------------------------------------------------------------------
# Publish/collect over the fleet KV plane
# ---------------------------------------------------------------------------


def test_publish_collect_roundtrip(tmp_path):
    from horovod_tpu.core.elastic import FileKV

    kv = FileKV(str(tmp_path))
    for rank in (0, 1):
        snap = {"v": 1, "rank": rank, "nproc": 2, "wall": time.time(),
                "generation": 3, "epoch": 9, "kind": "stall",
                "reason": None, "entries": [], "draining": None,
                "kv_failovers": 0, "exec_median_us": None}
        doctor.publish(kv, snap)
    got = doctor.collect(kv, 3, 9, 2)
    assert sorted(s["rank"] for s in got) == [0, 1]
    # exclude= skips the caller's own key; other epochs are invisible.
    assert [s["rank"] for s in doctor.collect(kv, 3, 9, 2, exclude=0)] \
        == [1]
    assert doctor.collect(kv, 3, 10, 2) == []


# ---------------------------------------------------------------------------
# Introspection: identical record shape from BOTH engines
# ---------------------------------------------------------------------------


class _GatedExecutor:
    def __init__(self):
        self.gate = threading.Event()

    def allreduce(self, flat, average):
        self.gate.wait(15.0)
        return flat.copy()


def test_inspect_record_shape_parity_both_engines(hvd):
    """The acceptance contract: both engines export the same per-entry
    record shape, key-for-key in ENGINE_INSPECT_KEYS order (hvdcheck
    rule parity-doctor pins the writers from source; this pins the
    runtime)."""
    from horovod_tpu.core.engine import Engine
    from horovod_tpu.core.native_engine import NativeEngine
    from horovod_tpu.core.timeline import Timeline

    tables = {}
    for label, make in (
            ("python", lambda x: Engine(executor=x,
                                        timeline=Timeline(None))),
            ("native", lambda x: NativeEngine(executor=x,
                                              timeline_path=""))):
        ex = _GatedExecutor()
        e = make(ex)
        try:
            h = e.allreduce_async("ins/x", np.ones((4,), np.float32),
                                  False)
            deadline = time.monotonic() + 5.0
            table = e.inspect()
            while time.monotonic() < deadline and not table:
                time.sleep(0.01)
                table = e.inspect()
            tables[label] = table
        finally:
            ex.gate.set()
            e.synchronize(h)
            e.shutdown()
    for label, table in tables.items():
        assert len(table) == 1, (label, table)
        rec = table[0]
        assert tuple(rec.keys()) == ENGINE_INSPECT_KEYS, (label, rec)
        assert rec["name"] == "ins/x" and rec["op"] == "allreduce"
        assert rec["dtype"] == "float32" and rec["bytes"] == 16
        assert rec["wire"] == "none" and rec["batch_n"] >= 1
        assert isinstance(rec["phase_age_us"], int)
        assert rec["phase_age_us"] >= 0
        assert rec["deadline_remaining_us"] is None  # no deadline set
        assert isinstance(rec["round"], int)


def test_native_inspect_grow_until_count_matches(hvd):
    """The inspect wire protocol: truncation is whole-record (every
    emitted line stays parseable JSON), the return value is the TRUE
    entry count, and growing the buffer until the parsed count matches
    it recovers every record — the loop NativeEngine.inspect runs."""
    from horovod_tpu.core.native_engine import NativeEngine

    ex = _GatedExecutor()
    e = NativeEngine(executor=ex, timeline_path="")
    names = [f"grow/{i:02d}" for i in range(12)]
    try:
        handles = [e.allreduce_async(n, np.ones((2,), np.float32), False)
                   for n in names]
        cap, truncated, records, total = 256, False, [], 0
        for _ in range(32):
            buf = ctypes.create_string_buffer(cap)
            total = int(e._lib.hvd_engine_inspect(e._ptr, buf, cap))
            lines = [ln for ln in buf.value.decode().splitlines() if ln]
            records = [json.loads(ln) for ln in lines]  # all complete
            if len(records) >= total:
                break
            truncated = True
            cap *= 2
        assert truncated, "256 bytes held 12 records? grow loop untested"
        assert total == len(names) and len(records) == total
        assert {r["name"] for r in records} == set(names)
        # The retired pending-names surface now rides the same table.
        assert set(e._pending_names()) == set(names)
        # And the public grow loop returns the full set in one call.
        assert {r["name"] for r in e.inspect()} == set(names)
    finally:
        ex.gate.set()
        for h in handles:
            e.synchronize(h)
        e.shutdown()


def test_python_engine_inspect_deadline_and_empty(hvd):
    from horovod_tpu.core.engine import Engine
    from horovod_tpu.core.timeline import Timeline

    ex = _GatedExecutor()
    e = Engine(executor=ex, timeline=Timeline(None))
    try:
        assert e.inspect() == []  # idle engine: empty table, no error
        h = e.allreduce_async("dl/x", np.ones((2,), np.float32), False,
                              deadline_ms=30_000.0)
        (rec,) = e.inspect()
        assert rec["deadline_remaining_us"] is not None
        assert 0 < rec["deadline_remaining_us"] <= 30_000_000
    finally:
        ex.gate.set()
        e.synchronize(h)
        e.shutdown()


# ---------------------------------------------------------------------------
# Hang-triggered dumps embed the inspect table + verdict (both engines)
# ---------------------------------------------------------------------------


def _wait_dump(tmp_path, timeout=8.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("hvd_flight.rank")
                 and f.endswith(".json")]
        if dumps:
            return json.load(open(os.path.join(tmp_path, dumps[0])))
        time.sleep(0.02)
    raise AssertionError("no flight dump written")


@pytest.mark.parametrize("engine_kind", ["python", "native"])
def test_stall_dump_embeds_inspect_and_verdict(hvd, tmp_path,
                                               monkeypatch, engine_kind):
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    from horovod_tpu.core.engine import Engine
    from horovod_tpu.core.native_engine import NativeEngine
    from horovod_tpu.core.timeline import Timeline

    ex = _GatedExecutor()
    if engine_kind == "python":
        e = Engine(executor=ex, stall_warning_s=0.05,
                   timeline=Timeline(None))
    else:
        e = NativeEngine(executor=ex, stall_warning_s=0.2,
                         timeline_path="")
    try:
        h = e.allreduce_async("stuck", np.ones((2,), np.float32), False)
        dump = _wait_dump(tmp_path)
        assert dump["kind"] == "stall"
        assert any(r["name"] == "stuck" for r in dump["inspect"])
        (rec,) = [r for r in dump["inspect"] if r["name"] == "stuck"]
        assert tuple(rec.keys()) == ENGINE_INSPECT_KEYS
        # One-rank world: the diagnosis ran (trigger stamped) even
        # though nothing cross-rank is attributable.
        assert dump["doctor"]["trigger"] == "stall"
        assert "findings" in dump["doctor"]
    finally:
        ex.gate.set()
        e.synchronize(h)
        e.shutdown()


def test_dump_rate_limit_is_kind_scoped(tmp_path, monkeypatch):
    """A prior unrelated dump must not suppress a hang post-mortem: the
    rate-limit key carries the dump kind, so the same reason head dumps
    once per kind inside the interval — and the same (kind, reason)
    repeat is still dropped."""
    import logging

    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    from horovod_tpu.core import timeline as tl

    log = logging.getLogger("test.doctor.ratelimit")
    reason = f"collide {time.monotonic()}"  # unique: the limiter is global
    assert tl.dump_and_warn([], reason, 0, log) is not None
    assert tl.dump_and_warn([], reason, 0, log, kind="stall") is not None
    assert tl.dump_and_warn([], reason, 0, log, kind="stall") is None


# ---------------------------------------------------------------------------
# hvd.diagnose() + sentinel + console surfaces
# ---------------------------------------------------------------------------


def test_hvd_diagnose_on_healthy_world(hvd):
    v = hvd.diagnose()
    assert v["trigger"] == "diagnose"
    assert "findings" in v and isinstance(v["ranks_reporting"], list)
    assert doctor.last_verdict() is v  # /doctor serves it between hangs


def test_automatic_empty_dump_keeps_standing_attribution(hvd, monkeypatch):
    """A poisoned engine keeps re-dumping empty negotiation rounds after
    the victims were culled: those findings-free automatic verdicts must
    not amnesia the standing diagnosis. Only an explicit
    ``hvd.diagnose()`` all-clear replaces it."""
    attributed = {
        "v": 1, "kind": "missing_submitter", "tensor": "g", "ranks": [1],
        "detail": "rank(s) [1] never announced 'g'",
        "findings": [{"kind": "missing_submitter", "tensor": "g",
                      "ranks": [1], "detail": "x"}],
        "ranks_reporting": [0], "nproc": 2, "wall_us": 1,
        "trigger": "stall"}
    monkeypatch.setattr(doctor, "_last_verdict", attributed)
    v = doctor.on_hang("negotiation failed: peer dead", "negotiation",
                       [], rank=0)
    # The triggering dump still embeds what THIS diagnosis saw...
    assert v is not None and v["kind"] is None
    # ...but the served verdict keeps the attribution.
    assert doctor.last_verdict() is attributed
    d = hvd.diagnose()
    assert doctor.last_verdict() is d


def test_sentinel_note_hang_records_verdict():
    from horovod_tpu.core import sentinel
    from horovod_tpu.core import telemetry as tele

    s = sentinel.get_sentinel()
    prev = s.last_verdict
    before = tele.REGISTRY.counter("sentinel.verdict.hang").snapshot()
    try:
        v = sentinel.note_hang(
            {"kind": "missing_submitter", "tensor": "grad/b",
             "ranks": [1], "wall_us": 1}, rank=0)
        assert v["origin"] == "doctor" and v["verdict"] == "hang"
        assert v["kind"] == "missing_submitter" and v["rank"] == 0
        assert s.last_verdict is v
        after = tele.REGISTRY.counter("sentinel.verdict.hang").snapshot()
        assert after == before + 1
    finally:
        s.last_verdict = prev  # do not leave /healthz degraded


def test_fleet_console_blames_tensor():
    from horovod_tpu.utils import stats

    out = stats.render_fleet({
        "size": 2, "epoch": 1, "generation": 0,
        "doctor": {"kind": "missing_submitter", "tensor": "grad/b",
                   "ranks": [1], "wall_us": 2}})
    assert "doctor: missing_submitter tensor='grad/b' rank(s) [1]" in out
    # No verdict -> no doctor line.
    assert "doctor:" not in stats.render_fleet(
        {"size": 2, "epoch": 1, "generation": 0, "doctor": None})


def test_fleet_merge_folds_newest_blame():
    from horovod_tpu.core import fleet

    base = {"counters": {}, "gauges": {}, "hists": {}, "rings": {},
            "generation": 0, "epoch": 0, "wall": time.time()}
    old = dict(base, rank=0, doctor={"kind": "slow_executor",
                                     "tensor": "a", "ranks": [0],
                                     "wall_us": 10})
    new = dict(base, rank=1, doctor={"kind": "missing_submitter",
                                     "tensor": "b", "ranks": [1],
                                     "wall_us": 20})
    report = fleet.merge_snapshots([old, new])
    assert report["doctor"]["kind"] == "missing_submitter"
    assert report["doctor"]["tensor"] == "b"


def test_stats_doctor_cli_over_dump_dir(capsys):
    from horovod_tpu.utils import stats

    assert stats.main([DATA, "--doctor"]) == 0
    out = capsys.readouterr().out
    assert "verdict=missing_submitter" in out
    assert "tensor='sync/only0'" in out and "rank(s) [1]" in out


def test_stats_doctor_cli_json_envelope(capsys):
    from horovod_tpu.utils import stats

    assert stats.main([DATA, "--doctor", "--json"]) == 0
    env = json.loads(capsys.readouterr().out)
    # The doctor verdict rides INSIDE the one-envelope shape.
    assert env["source"] == "doctor" and env["samples"] == []
    assert env["doctor"]["kind"] == "missing_submitter"
    assert stats.main([str(DATA) + "/does-not-exist", "--doctor"]) == 1
    assert "cannot build doctor view" in capsys.readouterr().out


def test_stats_doctor_single_file_and_saved_verdict(tmp_path, capsys):
    from horovod_tpu.utils import stats

    # A single dump file: one-rank view, nothing attributable.
    one = os.path.join(DATA, "hvd_flight.rank0.401.1754300001000000.json")
    assert stats._doctor_verdict_for(one)["kind"] is None
    # A saved verdict JSON (curl .../doctor body) passes through.
    saved = tmp_path / "verdict.json"
    saved.write_text(json.dumps(
        {"kind": "kv_degraded", "tensor": None, "ranks": [0],
         "findings": [{"kind": "kv_degraded", "ranks": [0],
                       "detail": "failover x2"}],
         "ranks_reporting": [0], "nproc": 1}))
    assert stats.main([str(saved), "--doctor"]) == 0
    assert "verdict=kv_degraded" in capsys.readouterr().out


def test_render_doctor_flags_unknown_kind():
    from horovod_tpu.utils import stats

    out = stats.render_doctor(
        {"kind": "exploded", "tensor": "t", "ranks": [2],
         "findings": [{"kind": "exploded", "detail": "boom"}],
         "ranks_reporting": [0], "nproc": 2})
    assert "unknown-kind(exploded)" in out
    # Findings render in vocabulary priority order.
    out = stats.render_doctor(
        {"kind": "dead_peer", "tensor": "t", "ranks": [1],
         "findings": [{"kind": "kv_degraded", "detail": "kv"},
                      {"kind": "dead_peer", "detail": "dp"}],
         "ranks_reporting": [0], "nproc": 2})
    assert out.index("dead_peer: dp") < out.index("kv_degraded: kv")


def test_doctor_http_arm(hvd):
    """GET /doctor triggers an on-demand diagnosis on the live rank."""
    from horovod_tpu.core import telemetry_http
    from horovod_tpu.utils import stats

    port = telemetry_http.maybe_start(0)
    assert port
    try:
        body = stats.fetch_http(f"http://127.0.0.1:{port}/doctor")
        v = json.loads(body)
        assert v["trigger"] == "diagnose" and "findings" in v
        # The 404 hint names the new arm.
        missing = stats.fetch_http(f"http://127.0.0.1:{port}/nope")
        assert "/doctor" in missing
    finally:
        telemetry_http.stop()
