"""Unit tests for the negotiation response cache — the bitvector fast
path of the engine control plane (horovod_tpu/core/coordinator.py).

Reference: horovod/common/response_cache.cc — steady-state coordination
collapses to a small bitvector exchange because a training loop submits
the SAME tensor set thousands of times (arxiv 1802.05799; the
MPI-coordination study 1810.11112 identifies per-tensor negotiation as
the dominant small-tensor overhead).

Pinned here: LRU/fingerprint/epoch semantics of :class:`ResponseCache`,
the full→fast round transition, set-intersection readiness, the
eviction-driven full-round fallback, KV garbage collection, and the
adversarial coherence case — one rank evicting mid-run must yield a
lockstep invalidation with nothing scheduled, never a stale hit."""

import threading

from horovod_tpu.core import telemetry as tele
from horovod_tpu.core.coordinator import (
    Coordinator,
    KVError,
    LocalKV,
    RequestMeta,
    ResponseCache,
    decide,
)


def meta(name, op="allreduce", dtype="float32", shape=(4,), **kw):
    import numpy as np

    nbytes = int(np.prod(shape)) * 4
    return RequestMeta(name=name, op=op, dtype=dtype, itemsize=4,
                       shape=tuple(shape), nbytes=nbytes, **kw)


class TestResponseCache:
    def test_lookup_requires_exact_identity(self):
        c = ResponseCache(8)
        c.insert(meta("x"))
        assert c.lookup(meta("x")) is not None
        # age_s is submit-time noise, never part of the identity.
        assert c.lookup(meta("x", age_s=3.5)) is not None
        # Any identity change — shape, dtype, op, root — is a miss.
        assert c.lookup(meta("x", shape=(8,))) is None
        assert c.lookup(meta("x", dtype="float64")) is None
        assert c.lookup(meta("x", op="broadcast")) is None
        assert c.lookup(meta("y")) is None

    def test_allgather_first_dim_change_is_a_miss(self):
        # _fingerprint wildcards allgather's dim 0 for cross-process
        # agreement; the CACHE must not — a varying first dim has to
        # renegotiate or peers would decode a stale size.
        c = ResponseCache(8)
        c.insert(meta("g", op="allgather", shape=(2, 3)))
        assert c.lookup(meta("g", op="allgather", shape=(2, 3))) is not None
        assert c.lookup(meta("g", op="allgather", shape=(5, 3))) is None

    def test_bits_roundtrip(self):
        assert ResponseCache.decode_mask(ResponseCache.encode(set())) == set()
        bits = {0, 3, 64, 700}
        assert ResponseCache.decode_mask(ResponseCache.encode(bits)) == bits

    def test_lru_eviction_bumps_epoch(self):
        c = ResponseCache(2)
        c.insert(meta("a"))
        c.insert(meta("b"))
        assert c.evict_over_capacity() == 0
        c.touch(["a"])  # b is now least-recently used
        c.insert(meta("c"))
        epoch0 = c.epoch
        assert c.evict_over_capacity() == 1
        assert c.lookup(meta("b")) is None          # evicted
        assert c.lookup(meta("a")) is not None
        assert c.lookup(meta("c")) is not None
        assert c.epoch == epoch0 + 1                # coherence signal

    def test_update_in_place_keeps_bit(self):
        c = ResponseCache(8)
        c.insert(meta("x"))
        bit = c.bit_of("x")
        c.insert(meta("x", shape=(16,)))
        assert c.bit_of("x") == bit
        assert c.lookup(meta("x", shape=(16,))) == bit
        assert c.lookup(meta("x")) is None

    def test_evicted_bits_are_reused(self):
        # Under name churn (alternating train/eval sets) the bitvector
        # mask must stay bounded by the live-set high-water mark, not
        # grow with cumulative insertions — evicted positions are
        # recycled smallest-first.
        c = ResponseCache(2)
        for cycle in range(50):
            c.insert(meta(f"a{cycle}"))
            c.insert(meta(f"b{cycle}"))
            c.evict_over_capacity()
        bits = {c.bit_of(n) for n in (f"a{49}", f"b{49}")}
        assert all(b is not None and b < 4 for b in bits), bits
        assert c._next_bit <= 4, c._next_bit

    def test_invalidate_clears_and_advances_epoch(self):
        c = ResponseCache(8)
        c.insert(meta("x"))
        c.invalidate()
        assert len(c) == 0 and c.epoch == 1
        c.invalidate(7)
        assert c.epoch == 7


class World:
    """N coordinators over one LocalKV, persisted across rounds — the
    steady-state (same coordinators, advancing rounds) the cache exists
    for, which run_round-style one-shot helpers cannot exercise."""

    def __init__(self, nproc=2, fusion=1 << 26, capacity=1024,
                 timeout_s=10.0, namespace="hvd/neg/cache-test"):
        self.store = {}
        self.coords = [
            Coordinator(LocalKV(self.store), nproc, p, 0.005, fusion,
                        timeout_s=timeout_s, cache_capacity=capacity,
                        namespace=namespace)
            for p in range(nproc)
        ]

    def round(self, per_pid):
        results = [None] * len(self.coords)
        errors = [None] * len(self.coords)

        def worker(p):
            try:
                results[p] = self.coords[p].negotiate(per_pid[p])
            except Exception as exc:
                errors[p] = exc

        threads = [threading.Thread(target=worker, args=(p,))
                   for p in range(len(self.coords))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        return results, errors


def group_names(decision, entries):
    return [[entries[i].name for i in g.indices] for g in decision.groups]


class TestFastRounds:
    def test_steady_state_takes_fast_path(self):
        tele.REGISTRY.reset()
        w = World()
        e = [meta("a"), meta("b")]
        results, errors = w.round({0: e, 1: e})
        assert errors == [None, None]
        # Round 0 was full (cold cache), every later round fast.
        assert not any(r.cached for r in results)
        for _ in range(2):
            results, errors = w.round({0: e, 1: e})
            assert errors == [None, None]
            assert all(r.cached for r in results)
        for c in w.coords:
            assert c.stats["fast_rounds"] == 2
        counters = tele.REGISTRY.flat_counters()
        assert counters["engine.negotiation.cache_hits"] > \
            counters["engine.negotiation.cache_misses"]
        assert "engine.negotiation.cache_invalidations" not in counters
        saved = tele.REGISTRY.gauge(
            "engine.negotiation.cache_bytes_saved").snapshot()
        assert saved > 0

    def test_fast_groups_match_full_round_groups(self):
        # The memoized fast-path composition must equal what decide()
        # produced on the identical full round — same fusion, same order.
        e = [meta("b"), meta("a"), meta("c", dtype="float64")]
        w = World()
        (full0, full1), errs = w.round({0: e, 1: e})
        assert errs == [None, None] and not full0.cached
        (fast0, fast1), errs = w.round({0: e, 1: e})
        assert errs == [None, None] and fast0.cached and fast1.cached
        assert group_names(fast0, e) == group_names(full0, e)
        assert group_names(fast1, e) == group_names(full1, e)
        ref = decide({0: e, 1: e}, e, 1 << 26)
        assert [g.indices for g in fast0.groups] == \
            [g.indices for g in ref]

    def test_partial_announce_intersects(self):
        # Rank 1 has not (re)submitted 'b' yet: both ranks all-hit, the
        # round stays FAST, and readiness is the bit intersection — only
        # 'a' executes, 'b' stays pending with no stale decision.
        w = World()
        both = [meta("a"), meta("b")]
        only_a = [meta("a")]
        w.round({0: both, 1: both})  # warm (full)
        (r0, r1), errs = w.round({0: both, 1: only_a})
        assert errs == [None, None]
        assert r0.cached and r1.cached
        assert group_names(r0, both) == [["a"]]
        assert group_names(r1, only_a) == [["a"]]

    def test_changed_tensor_set_forces_full_round(self):
        w = World()
        e1 = [meta("a"), meta("b")]
        w.round({0: e1, 1: e1})
        assert w.round({0: e1, 1: e1})[0][0].cached
        e2 = [meta("a"), meta("c")]  # 'c' is new: a miss on every rank
        (r0, r1), errs = w.round({0: e2, 1: e2})
        assert errs == [None, None]
        assert not r0.cached and not r1.cached
        assert group_names(r0, e2) == [["a", "c"]]
        # The new set is cached now — next round is fast again.
        (r0, r1), errs = w.round({0: e2, 1: e2})
        assert r0.cached and r1.cached

    def test_shape_change_is_miss_then_recached(self):
        w = World()
        e1 = [meta("x", shape=(4,))]
        w.round({0: e1, 1: e1})
        assert w.round({0: e1, 1: e1})[0][0].cached
        e2 = [meta("x", shape=(8,))]
        (r0, _), errs = w.round({0: e2, 1: e2})
        assert errs == [None, None] and not r0.cached
        assert group_names(r0, e2) == [["x"]]
        (r0, _), errs = w.round({0: e2, 1: e2})
        assert r0.cached

    def test_eviction_forces_full_round_fallback(self):
        tele.REGISTRY.reset()
        w = World(capacity=2)
        e = [meta("a"), meta("b"), meta("c")]
        (r0, _), errs = w.round({0: e, 1: e})
        assert errs == [None, None] and not r0.cached
        # Three agreed tensors into a capacity-2 cache: one was evicted
        # (epoch advanced, lockstep on both ranks) — so the steady set
        # can never go fully fast, but every round stays CORRECT.
        for c in w.coords:
            assert len(c.cache) == 2
            assert c.cache.evictions >= 1
        epochs = {c.cache.epoch for c in w.coords}
        assert len(epochs) == 1  # lockstep eviction
        (r0, r1), errs = w.round({0: e, 1: e})
        assert errs == [None, None]
        assert not r0.cached  # the evicted tensor missed -> full round
        assert group_names(r0, e) == [["a", "b", "c"]]
        counters = tele.REGISTRY.flat_counters()
        assert counters["engine.negotiation.cache_invalidations"] >= 2

    def test_adversarial_one_rank_evicts_midrun(self):
        """Coherence under divergence: one rank drops a cache entry on
        its own (never happens in lockstep operation — this is the
        adversarial case). The next round must observe the epoch
        mismatch on EVERY rank, schedule NOTHING (a stale hit is
        structurally impossible), clear caches in lockstep, and
        renegotiate fully."""
        tele.REGISTRY.reset()
        w = World()
        e = [meta("a"), meta("b")]
        w.round({0: e, 1: e})
        assert w.round({0: e, 1: e})[0][0].cached  # steady state
        w.coords[1].cache.evict("a")  # the adversarial divergence
        (r0, r1), errs = w.round({0: e, 1: e})
        assert errs == [None, None]
        # Nothing scheduled anywhere — entries stay pending.
        assert r0.groups == [] and r1.groups == []
        assert not r0.cached and not r1.cached
        # Lockstep reset: both caches empty at the SAME fresh epoch.
        assert len(w.coords[0].cache) == 0
        assert len(w.coords[1].cache) == 0
        assert w.coords[0].cache.epoch == w.coords[1].cache.epoch
        counters = tele.REGISTRY.flat_counters()
        assert counters["engine.negotiation.cache_invalidations"] >= 2
        # The next round renegotiates with full tables and recovers.
        (r0, r1), errs = w.round({0: e, 1: e})
        assert errs == [None, None]
        assert not r0.cached
        assert group_names(r0, e) == [["a", "b"]]
        assert group_names(r1, e) == [["a", "b"]]
        # ... and the one after is fast again.
        assert w.round({0: e, 1: e})[0][0].cached

    def test_idle_rounds_ride_the_fast_path(self):
        w = World()
        e = [meta("a")]
        w.round({0: e, 1: e})
        (r0, _), errs = w.round({0: [], 1: []})
        assert errs == [None, None]
        assert r0.cached and r0.groups == []
        assert r0.idle_backoff_s > 0

    def test_capacity_zero_disables_cache(self):
        w = World(capacity=0)
        e = [meta("a")]
        for c in w.coords:
            assert c.cache is None
        w.round({0: e, 1: e})
        (r0, _), errs = w.round({0: e, 1: e})
        assert errs == [None, None] and not r0.cached

    def test_aggregate_mode_disables_cache(self, monkeypatch):
        monkeypatch.setenv("HVD_NEGOTIATION_AGGREGATE", "1")
        c = Coordinator(LocalKV({}), 2, 0, 0.005, 0, timeout_s=1.0,
                        cache_capacity=1024)
        assert c.aggregate and c.cache is None

    def test_mixed_capacity_fails_fast(self):
        # HVD_CACHE_CAPACITY must be identical on every process. Every
        # cache-carrying message names its capacity, so ANY mix fails
        # fast on the FIRST round, on every rank, by name — zero vs
        # nonzero, and two different nonzero values (whose lone-rank
        # evictions would otherwise cycle the world through endless
        # epoch resets).
        e = [meta("a")]
        w = World()
        w.coords[1].cache = None  # rank 1 "configured" cache-off
        _, errors = w.round({0: e, 1: e})
        assert all(isinstance(err, KVError) for err in errors), errors
        assert all("HVD_CACHE_CAPACITY mismatch" in str(err)
                   for err in errors), errors

        w2 = World(namespace="hvd/neg/cache-test2")
        w2.coords[1].cache = ResponseCache(512)  # nonzero, but different
        _, errors = w2.round({0: e, 1: e})
        assert all(isinstance(err, KVError) for err in errors), errors
        assert "512" in str(errors[0]) and "1024" in str(errors[0])

    def test_params_propagate_on_fast_rounds(self):
        # The autotuner's values ride EVERY round, fast ones included
        # (reference: ParameterManager::SyncParams).
        w = World()
        e = [meta("a")]
        w.round({0: e, 1: e})
        w.coords[0].cycle_time_s = 0.042
        w.coords[0].fusion_threshold = 12345
        (r0, r1), errs = w.round({0: e, 1: e})
        assert errs == [None, None] and r0.cached
        assert r1.cycle_time_s == 0.042
        assert r1.fusion_threshold == 12345
        assert w.coords[1].cycle_time_s == 0.042

    def test_round_keys_garbage_collected(self):
        # Long trainings must not grow the KV store: every consumed
        # round key is reclaimed (fast rounds included) — only the
        # latest round's keys may linger.
        w = World()
        e = [meta("a"), meta("b")]
        for _ in range(6):
            _, errs = w.round({0: e, 1: e})
            assert errs == [None, None]
        round_keys = [k for k in w.store
                      if isinstance(k, str) and "/r" in k]
        assert all("/r5/" in k for k in round_keys), sorted(w.store)
