"""Zero-copy engine data plane (core/bufferpool.py + the engines'
pooled/donated submit paths).

Pins, for BOTH engines:

- the allocation-free steady state: pool misses stop growing after
  warmup (the PersistentBuffer claim, SURVEY C8, as a regression test);
- snapshot semantics under adversarial mutation: a caller scribbling on
  its buffers immediately after every ``*_async`` submit cannot change
  what gets reduced — digests match an untouched-world run bitwise;
- donation semantics: ``donate=True`` skips the snapshot, the engine
  never writes the donated buffer, and mutating a donated numpy array
  raises (the view is flagged unwriteable);
- pool hygiene: ``abandon()`` poisons the dying engine's pool (leaked
  slabs can never be handed out again) and the ``engine.pool:exhausted``
  fault site forces the cap-reached path on demand.
"""

import ctypes
import hashlib
import threading

import numpy as np
import pytest

from horovod_tpu.core import bufferpool as bpool
from horovod_tpu.core import engine as eng
from horovod_tpu.core import faultline as flt
from horovod_tpu.core import native
from horovod_tpu.core import timeline as tl
from horovod_tpu.core.native_engine import NativeEngine


class EchoExecutor:
    """Deterministic local data plane: allreduce doubles, allgather
    tiles x2, broadcast adds 100 (float) — engine-independent results."""

    def allreduce(self, flat, average):
        return flat * 2.0 if flat.dtype.kind == "f" else flat * 2

    def allgather(self, t):
        return np.tile(t, (2,) + (1,) * (t.ndim - 1))

    def broadcast(self, t, root):
        return t + 100.0 if t.dtype.kind == "f" else t.copy()


class GatedEcho(EchoExecutor):
    """First call blocks until release() — submits pile up while the
    caller mutates its buffers, the adversarial race this file pins."""

    def __init__(self):
        self.gate = threading.Event()
        self.started = threading.Event()
        self.calls = 0

    def _pause(self):
        self.calls += 1
        if self.calls == 1:
            self.started.set()
            self.gate.wait(10.0)

    def allreduce(self, flat, average):
        self._pause()
        return super().allreduce(flat, average)

    def allgather(self, t):
        self._pause()
        return super().allgather(t)

    def broadcast(self, t, root):
        self._pause()
        return super().broadcast(t, root)


def _mk(impl, executor, **kw):
    kw.setdefault("cycle_time_s", 0.002)
    if impl == "native":
        kw.setdefault("timeline_path", "")
        return NativeEngine(executor=executor, **kw)
    kw.setdefault("timeline", tl.Timeline(None))
    return eng.Engine(executor=executor, **kw)


# ---------------------------------------------------------------------------
# BufferPool unit semantics
# ---------------------------------------------------------------------------

def test_pool_recycles_on_release():
    p = bpool.BufferPool(max_bytes=1 << 20)
    a = p.checkout(1024, np.float32)
    a[:] = 7.0
    assert p.stats()["misses"] == 1
    # Slab pinned while a view lives: a second checkout cannot reuse it.
    b = p.checkout(1024, np.float32)
    assert p.stats()["misses"] == 2
    assert not np.shares_memory(a, b)
    del a, b
    # Both slabs free again: the next two checkouts are hits.
    c = p.checkout(1024, np.float32)
    d = p.checkout(512, np.float32)  # same 4 KiB class
    assert p.stats()["hits"] == 2
    assert p.stats()["misses"] == 2
    del c, d


def test_pool_derived_views_pin_the_slab():
    p = bpool.BufferPool(max_bytes=1 << 20)
    a = p.checkout(256, np.float32)
    view = a.reshape(16, 16)[3:5]
    del a
    # A grandchild view still pins the slab (numpy collapses view chains
    # onto the owning array) — reuse now would scribble on `view`.
    b = p.checkout(256, np.float32)
    assert p.stats()["hits"] == 0
    assert not np.shares_memory(view, b)
    del view, b


def test_pool_per_dtype_and_class():
    p = bpool.BufferPool(max_bytes=1 << 20)
    f = p.checkout(100, np.float32)
    del f
    # Same class, different dtype: no cross-dtype reuse.
    i = p.checkout(100, np.int32)
    assert p.stats()["hits"] == 0
    del i


def test_pool_disabled_and_capped():
    off = bpool.BufferPool(max_bytes=0)
    assert not off.enabled
    x = off.checkout(64, np.float32)
    del x
    y = off.checkout(64, np.float32)
    assert off.stats() == {"hits": 0, "misses": 2, "checkouts": 2,
                           "bound_hits": 0, "bytes_resident": 0}
    del y
    # Cap: one 4 KiB slab fits, the second is not retained.
    small = bpool.BufferPool(max_bytes=4096)
    a = small.checkout(1024, np.float32)
    b = small.checkout(1024, np.float32)
    assert small.stats()["bytes_resident"] == 4096
    del a, b
    c = small.checkout(1024, np.float32)
    assert small.stats()["hits"] == 1  # the retained slab came back
    del c


def test_pool_poison_never_reuses():
    p = bpool.BufferPool(max_bytes=1 << 20)
    a = p.checkout(1024, np.float32)
    p.poison()
    assert p.poisoned
    del a
    b = p.checkout(1024, np.float32)
    assert p.stats()["hits"] == 0
    assert p.stats()["bytes_resident"] == 0
    del b


def test_pool_exhausted_fault_site():
    try:
        flt.configure("engine.pool:exhausted:2")
        p = bpool.BufferPool(max_bytes=1 << 20)
        a = p.checkout(1024, np.float32)
        del a
        b = p.checkout(1024, np.float32)  # second exhausted firing
        del b
        # Both firings allocated fresh without retaining.
        assert p.stats()["misses"] == 2
        assert p.stats()["bytes_resident"] == 0
        c = p.checkout(1024, np.float32)  # spec spent: pools again
        assert p.stats()["bytes_resident"] == 4096
        del c
    finally:
        flt.reset()


# ---------------------------------------------------------------------------
# Allocation-free steady state (the pinned regression test, both engines)
# ---------------------------------------------------------------------------

def _native_pool_misses(e):
    st = native.HvdStats()
    e._lib.hvd_engine_get_stats(e._ptr, ctypes.byref(st))
    return int(st.pool_misses) + e._pool.misses


@pytest.mark.parametrize("impl", ["python", "native"])
def test_steady_state_pool_misses_flat(impl):
    """N steady-state cycles with a fixed working set: after warmup the
    pool serves every submit snapshot, fusion buffer and result buffer
    from reused slabs — the miss counter must stop growing (the
    allocation-free claim of ROADMAP item 5, pinned)."""
    ex = EchoExecutor()
    e = _mk(impl, ex)
    try:
        tensors = [np.full((1024,), float(k), np.float32)
                   for k in range(4)]

        def one_iter():
            # Synchronize after each submit: single-entry cycles, so the
            # cycle composition (and therefore the slab classes) is
            # deterministic — no composition-dependent late misses.
            for k, t in enumerate(tensors):
                h = e.allreduce_async(f"steady/{k}", t, average=False)
                np.testing.assert_allclose(e.synchronize(h),
                                           np.full((1024,), 2.0 * k))
            h = e.allgather_async("steady/g", tensors[1])
            e.synchronize(h)
            h = e.broadcast_async("steady/b", tensors[2], 0)
            e.synchronize(h)

        for _ in range(12):
            one_iter()
        warm = _native_pool_misses(e) if impl == "native" else e.pool.misses
        assert warm > 0  # the pool is actually in the path
        for _ in range(25):
            one_iter()
        final = (_native_pool_misses(e) if impl == "native"
                 else e.pool.misses)
        assert final == warm, (
            f"{impl} engine still allocating in steady state: "
            f"pool misses {warm} -> {final}")
        hits = (e._pool.hits if impl == "native" else e.pool.hits)
        assert hits > 0
    finally:
        e.shutdown()


# ---------------------------------------------------------------------------
# Adversarial snapshot semantics (both engines)
# ---------------------------------------------------------------------------

def _digest(arrays):
    return hashlib.sha256(
        b"".join(np.ascontiguousarray(a).tobytes()
                 for a in arrays)).hexdigest()


def _submit_all(e, bufs, donate=False):
    return [
        e.allreduce_async("adv/r", bufs[0], average=False, donate=donate),
        e.allgather_async("adv/g", bufs[1], donate=donate),
        e.broadcast_async("adv/b", bufs[2], 1, donate=donate),
    ]


def _fresh_bufs():
    return [np.arange(256, dtype=np.float32),
            np.linspace(-1.0, 1.0, 48, dtype=np.float32).reshape(4, 12),
            np.full((33,), 3.25, np.float32)]


@pytest.mark.parametrize("impl", ["python", "native"])
def test_mutate_after_submit_does_not_change_reduction(impl):
    """The architecture invariant, adversarially: the caller scribbles
    over every buffer immediately after its *_async call, while the
    executor is provably still blocked — the reduced digests must equal
    the untouched-world run bitwise."""
    # Control: untouched world.
    e = _mk(impl, EchoExecutor())
    try:
        handles = _submit_all(e, _fresh_bufs())
        control = _digest([e.synchronize(h) for h in handles])
    finally:
        e.shutdown()

    ex = GatedEcho()
    e = _mk(impl, ex)
    try:
        bufs = _fresh_bufs()
        handles = [e.allreduce_async("adv/r", bufs[0], average=False)]
        bufs[0][:] = -777.0  # mutate IMMEDIATELY after submit
        assert ex.started.wait(10.0)  # executor is wedged mid-batch
        handles.append(e.allgather_async("adv/g", bufs[1]))
        bufs[1][:] = np.nan
        handles.append(e.broadcast_async("adv/b", bufs[2], 1))
        bufs[2][:] = 0.0
        ex.gate.set()
        mutated = _digest([e.synchronize(h) for h in handles])
    finally:
        ex.gate.set()
        e.shutdown()
    assert mutated == control


@pytest.mark.parametrize("impl", ["python", "native"])
def test_donate_then_mutate_raises_and_reduces_correctly(impl):
    """donate=True hands the buffer over: the numpy array is flagged
    unwriteable, so a donate-then-mutate raises instead of corrupting
    the reduction; results match the snapshot path bitwise, and the
    engine never writes the donated buffer (it is read-only to it)."""
    e = _mk(impl, EchoExecutor())
    try:
        handles = _submit_all(e, _fresh_bufs())
        control = _digest([e.synchronize(h) for h in handles])
    finally:
        e.shutdown()

    ex = GatedEcho()
    e = _mk(impl, ex)
    try:
        bufs = _fresh_bufs()
        keep = [b.copy() for b in bufs]
        handles = _submit_all(e, bufs, donate=True)
        for b in bufs:
            with pytest.raises(ValueError):
                b[0] = 123.0  # donated: mutation must raise
        ex.gate.set()
        donated = _digest([e.synchronize(h) for h in handles])
        # The engine only ever READ the donated buffers.
        for b, k in zip(bufs, keep):
            np.testing.assert_array_equal(b, k)
    finally:
        ex.gate.set()
        e.shutdown()
    assert donated == control


@pytest.mark.parametrize("impl", ["python", "native"])
def test_rejected_donation_restores_writability(impl):
    """A REJECTED donated submit (duplicate name) must hand the buffer
    back writable: the engine never took ownership, and a permanently
    read-only caller buffer would be a silent resource-state leak."""
    ex = GatedEcho()
    e = _mk(impl, ex)
    try:
        first = np.ones((8,), np.float32)
        h = e.allreduce_async("rej/x", first, average=False, donate=True)
        dup = np.ones((8,), np.float32)
        with pytest.raises(eng.DuplicateNameError):
            e.allreduce_async("rej/x", dup, average=False, donate=True)
        dup[0] = 5.0  # ownership stayed with the caller
        ex.gate.set()
        e.synchronize(h)
        # The accepted donation stays frozen.
        with pytest.raises(ValueError):
            first[0] = 5.0
    finally:
        ex.gate.set()
        e.shutdown()


# ---------------------------------------------------------------------------
# Pool hygiene on the elastic path
# ---------------------------------------------------------------------------

def test_abandon_poisons_pool_python():
    e = _mk("python", EchoExecutor())
    pool = e.pool
    lent = pool.checkout(1024, np.float32)  # a slab "in flight"
    e.abandon()
    assert pool.poisoned
    del lent
    again = pool.checkout(1024, np.float32)
    assert pool.stats()["hits"] == 0  # nothing the old engine lent comes back
    del again
    # A successor engine starts with a fresh, working pool.
    e2 = _mk("python", EchoExecutor())
    try:
        assert e2.pool is not pool and not e2.pool.poisoned
        h = e2.allreduce_async("post/r", np.ones((8,), np.float32), False)
        np.testing.assert_allclose(e2.synchronize(h), np.full((8,), 2.0))
    finally:
        e2.shutdown()


def test_abandon_poisons_pool_native():
    e = _mk("native", EchoExecutor())
    pool = e._pool
    buf = np.ones((16,), np.float32)
    h = e.allreduce_async("aband/r", buf, False, donate=True)
    e.synchronize(h)
    e.abandon()
    assert pool.poisoned
    # The donated-buffer pin survives the abandonment (the parked C++
    # loop may still reference it) — the keepalive map is NOT cleared.
    e2 = _mk("native", EchoExecutor())
    try:
        assert e2._pool is not pool and not e2._pool.poisoned
        h = e2.allreduce_async("post/r", np.ones((8,), np.float32), False)
        np.testing.assert_allclose(e2.synchronize(h), np.full((8,), 2.0))
    finally:
        e2.shutdown()
