"""Faultline (ISSUE 11): deterministic fault injection — unit tier.

Every injection site is exercised on the 8-device single-process mesh
(no subprocess worlds): the spec grammar, the KV wrapper sites through
LocalKV, the heartbeat sites through an ElasticWorld on a LocalKV, both
engines' submit/exec sites, the checkpoint torn-write site (and the
crash-atomic save it regresses), the KV-plane failover it makes
testable, and the zero-overhead/no-spec pin the acceptance demands.
"""

import json
import os
import time

import numpy as np
import pytest

from horovod_tpu.core import faultline as flt


@pytest.fixture(autouse=True)
def _disarm():
    """Every test starts and ends disarmed — faultline state is
    process-global and must never leak across tests."""
    flt.reset()
    yield
    flt.reset()


# ---------------------------------------------------------------------------
# spec grammar
# ---------------------------------------------------------------------------


def test_spec_grammar_counts_offsets_and_errors():
    flt.configure("kv.get:delay:2:0.5,hb.beat:skip:*@3,ckpt.write:torn:1")
    assert flt.armed()
    spec = flt.active_spec()
    assert "kv.get:delay:2:0.5" in spec
    assert "hb.beat:skip:*@3" in spec
    assert "ckpt.write:torn:1" in spec
    # '@M' delays the first firing to the M-th arming.
    assert [flt.heartbeat() for _ in range(4)] == [None, None, "skip",
                                                  "skip"]
    # Counts exhaust.
    assert flt.ckpt_write() is not None
    assert flt.ckpt_write() is None
    for bad in ("nosuchsite:delay:1", "kv.get:nosuchmode:1",
                "kv.get:delay", "kv.get:delay:x", "kv.get:delay:-1",
                "kv.get:delay:1@0", "kv.get:delay:200%"):
        with pytest.raises(flt.FaultSpecError):
            flt.configure(bad)


def test_probabilistic_trigger_is_seed_deterministic():
    flt.configure("kv.try_get:vanish:40%", seed=11)
    a = [flt.kv_try_get("k") for _ in range(32)]
    flt.configure("kv.try_get:vanish:40%", seed=11)
    b = [flt.kv_try_get("k") for _ in range(32)]
    assert a == b
    assert any(a) and not all(a)  # it actually fires, and not always


def test_every_firing_is_counted_and_recorded():
    from horovod_tpu.core import telemetry as tele

    total0 = tele.REGISTRY.counter("fault.injected").value
    site0 = tele.REGISTRY.counter("fault.injected.kv.set").value
    flt.configure("kv.set:torn:2")
    assert flt.kv_set("k", "abcd") == "ab"
    assert flt.kv_set("k", "abcd") == "ab"
    assert flt.kv_set("k", "abcd") == "abcd"  # exhausted
    assert tele.REGISTRY.counter("fault.injected").value == total0 + 2
    assert tele.REGISTRY.counter("fault.injected.kv.set").value == site0 + 2
    recs = flt.snapshot()
    assert len(recs) == 2
    assert all(r["site"] == "kv.set" and r["mode"] == "torn"
               for r in recs)


# ---------------------------------------------------------------------------
# zero-overhead / no-spec pin (acceptance: byte-identical behavior)
# ---------------------------------------------------------------------------


def test_no_spec_is_inert_everywhere(hvd):
    """Disarmed, every site helper is an identity/no-op, nothing is
    recorded, and an engine round trip reduces exactly as without the
    subsystem."""
    assert not flt.armed()
    assert flt.check("kv.get") is None
    assert flt.kv_set("k", "value") == "value"
    assert flt.kv_get("k") is None
    assert flt.kv_try_get("k") is False
    assert flt.heartbeat() is None
    assert flt.engine_submit("t") is None
    assert flt.engine_exec("allreduce") is None
    assert flt.ckpt_write() is None
    assert flt.snapshot() == []
    assert flt.active_spec() is None
    from horovod_tpu.core.engine import Engine

    e = Engine(cycle_time_s=0.001)
    try:
        x = np.arange(8, dtype=np.float32)
        h = e.allreduce_async("flt_inert", x, average=False)
        out = e.synchronize(h)
        np.testing.assert_array_equal(out, x * hvd.size())
    finally:
        e.shutdown()


def test_bad_spec_fails_loudly():
    """A chaos run with a silently-dropped spec would 'pass' while
    testing nothing — misparse must raise, not warn."""
    with pytest.raises(flt.FaultSpecError, match="unknown fault site"):
        flt.configure("kv.gte:delay:1")


# ---------------------------------------------------------------------------
# KV wrapper sites (LocalKV — the same code path JaxKV wraps)
# ---------------------------------------------------------------------------


def test_kv_sites_delay_error_torn_vanish():
    from horovod_tpu.core import coordinator as coord

    kv = coord.LocalKV({})
    flt.configure("kv.set:torn:1,kv.get:error:1,kv.try_get:vanish:1,"
                  "kv.get:delay:1:0.15")
    kv.set("a", "0123456789")
    assert kv.try_get("a") is None          # vanish: reads absent once
    assert kv.try_get("a") == "01234"       # the torn write landed
    with pytest.raises(coord.KVError, match="injected fault"):
        kv.get("a", 1.0)                    # error: KVError, like organic
    t0 = time.monotonic()
    assert kv.get("a", 1.0) == "01234"      # delay: slow KV read
    assert time.monotonic() - t0 >= 0.14


def test_kv_error_fault_poisons_a_negotiation_round():
    """An injected KV error fails the round the way an organic KV
    failure does: KVError out of negotiate(), tombstone published, NOT
    rated as a clean shutdown (so the flight recorder dumps)."""
    from horovod_tpu.core import coordinator as coord

    store = {}
    c = coord.Coordinator(coord.LocalKV(store), 2, 0, 0.005, 0,
                          timeout_s=5.0)
    # '*': the clock-anchor exchange swallows KV errors by design — the
    # ROUND publish must hit the fault too.
    flt.configure("kv.set:error:*")
    with pytest.raises(coord.KVError, match="injected fault") as ei:
        c.negotiate([])
    assert not coord.is_shutdownish(ei.value)
    assert c.dead is not None  # poisoned, like any failed round


# ---------------------------------------------------------------------------
# heartbeat sites (ElasticWorld on a LocalKV)
# ---------------------------------------------------------------------------


def _world(tmp_path, monkeypatch, pid=0, nproc=2, lease="0.2"):
    monkeypatch.setenv("HVD_ELASTIC", "1")
    monkeypatch.setenv("HVD_ELASTIC_LEASE_S", lease)
    monkeypatch.setenv("HVD_ELASTIC_GRACE_S", "30")
    monkeypatch.setenv("HVD_ELASTIC_DIR", str(tmp_path))
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path / "flight"))
    monkeypatch.setenv("HVD_FLIGHT_MIN_INTERVAL", "0")
    from horovod_tpu.core import coordinator as coord, elastic

    store = {}
    w = elastic.ElasticWorld()
    w.active = True
    w.pid, w.nproc = pid, nproc
    w.live = list(range(nproc))
    w._kv = coord.LocalKV(store)
    return w, store


def test_heartbeat_fault_modes(tmp_path, monkeypatch):
    w, store = _world(tmp_path, monkeypatch)
    store["hvd/elastic/g0/hb/p1"] = "1"
    w._beat_once()
    assert store["hvd/elastic/g0/hb/p0"] == "1"
    # skip: counter frozen (no write at all this tick)
    flt.configure("hb.beat:skip:1")
    w._beat_once()
    assert store["hvd/elastic/g0/hb/p0"] == "1"
    # freeze: key rewritten but the counter does not advance
    flt.configure("hb.beat:freeze:1")
    w._beat_once()
    assert store["hvd/elastic/g0/hb/p0"] == "1"
    # vanish: the key disappears outright
    flt.configure("hb.beat:vanish:1")
    w._beat_once()
    assert "hvd/elastic/g0/hb/p0" not in store
    # disarmed again: the beat resumes where the counter left off
    flt.reset()
    w._beat_once()
    assert int(store["hvd/elastic/g0/hb/p0"]) >= 2


def test_frozen_beats_yield_lease_expiry_not_noshow(tmp_path,
                                                    monkeypatch):
    """A peer whose beats FREEZE (process alive, counter stopped) gets
    the 'lease expired' verdict — distinguishable from the startup
    no-show ('grace') and from a vanished key: the attribution the
    frozen-heartbeat chaos scenario pins end to end."""
    w, store = _world(tmp_path, monkeypatch)
    store["hvd/elastic/g0/hb/p1"] = "7"
    w._beat_once()
    time.sleep(0.25)  # counter never advances past the lease
    w._beat_once()
    assert 1 in w.dead
    assert "lease expired" in w.dead[1]
    assert "grace" not in w.dead[1] and "vanished" not in w.dead[1]


def test_beats_are_mirrored_to_the_file_plane(tmp_path, monkeypatch):
    w, store = _world(tmp_path, monkeypatch)
    store["hvd/elastic/g0/hb/p1"] = "1"
    w._beat_once()
    fkv = w._get_file_kv()
    assert fkv is not None
    assert fkv.try_get("hvd/elastic/g0/hb/p0") == "1"
    w._beat_once()
    assert fkv.try_get("hvd/elastic/g0/hb/p0") == "2"


# ---------------------------------------------------------------------------
# KV-plane failover (rank-0 death becomes an attributed verdict)
# ---------------------------------------------------------------------------


class _DeadKV:
    """A coordination service that stopped answering (its host died)."""

    def _die(self, *a, **k):
        from horovod_tpu.core.coordinator import KVError

        raise KVError("injected-dead coordination service")

    set = get = try_get = delete = _die


def test_kv_failover_attributed_verdict(tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_ELASTIC_KV_FAILOVER_S", "0.2")
    w, _ = _world(tmp_path, monkeypatch, pid=1)
    fkv = w._get_file_kv()
    # p0 beat (mirrored) before it died together with the service.
    fkv.set("hvd/elastic/g0/hb/p0", "7")
    w._beats[0] = ("7", time.monotonic())
    w._kv = _DeadKV()
    w._beat_once()  # first failure: the failover clock starts
    assert not w._failed_over
    time.sleep(0.25)
    w._beat_once()  # past the window: cut over
    assert w._failed_over
    assert w.dead == {}  # fresh lease at cutover — no instant verdict
    time.sleep(0.25)     # p0 stays silent on the file plane too
    w._beat_once()
    assert 0 in w.dead
    assert "fallback file KV plane" in w.dead[0]
    assert w.world_changed()
    # Tombstone mirrored; our own beats continued through the cutover.
    assert fkv.try_get("hvd/elastic/g0/dead/p0") is not None
    assert int(fkv.try_get("hvd/elastic/g0/hb/p1")) >= 2
    from horovod_tpu.core import telemetry as tele

    assert tele.REGISTRY.counter("world.kv_failovers").value >= 1
    assert w.summary()["kv_plane"] == "file"


def test_no_file_plane_keeps_supervisor_territory(tmp_path,
                                                  monkeypatch):
    """Without HVD_ELASTIC_DIR there is nothing to fail over to — the
    beat loop keeps returning to the supervisor-territory behavior
    (no failover flag, no spurious verdicts)."""
    w, _ = _world(tmp_path, monkeypatch, pid=1)
    monkeypatch.delenv("HVD_ELASTIC_DIR")
    monkeypatch.setenv("HVD_ELASTIC_KV_FAILOVER_S", "0.1")
    w._file_kv = None
    w._kv = _DeadKV()
    w._beat_once()
    time.sleep(0.15)
    w._beat_once()
    w._beat_once()
    assert not w._failed_over and w.dead == {}


def test_filekv_basics(tmp_path):
    from horovod_tpu.core.elastic import FileKV

    kv = FileKV(str(tmp_path / "kv"))
    assert kv.try_get("a/b") is None
    kv.set("a/b", "one")
    kv.set("a/b", "two")  # overwrite-in-place (rename)
    assert kv.try_get("a/b") == "two"
    assert kv.get("a/b", 0.1) == "two"
    t0 = time.monotonic()
    assert kv.get("absent", 0.2) is None  # timeout -> None, no raise
    assert time.monotonic() - t0 >= 0.19
    kv.delete("a/b")
    assert kv.try_get("a/b") is None
    kv.delete("a/b")  # idempotent


# ---------------------------------------------------------------------------
# engine sites — both engines through the same shim
# ---------------------------------------------------------------------------


def _engines():
    from horovod_tpu.core.engine import Engine

    out = [("python", Engine)]
    try:
        from horovod_tpu.core.native_engine import NativeEngine

        out.append(("native", NativeEngine))
    except Exception:  # no toolchain: python twin still covers the shim
        pass
    return out


@pytest.mark.parametrize("name,cls", _engines())
def test_engine_submit_and_exec_faults(hvd, name, cls):
    eng = cls(cycle_time_s=0.001)
    try:
        x = np.arange(8, dtype=np.float32)
        # submit failure: raises at *_async, nothing enqueued.
        flt.configure("engine.submit:fail:1")
        from horovod_tpu.core.engine import EngineError

        with pytest.raises(EngineError, match="injected fault"):
            eng.allreduce_async("flt_sub", x, average=False)
        h = eng.allreduce_async("flt_sub", x, average=False)
        np.testing.assert_array_equal(eng.synchronize(h),
                                      x * hvd.size())
        # poisoned result: the reduced value comes back NaN.
        flt.configure("engine.exec:poison:1")
        h = eng.allreduce_async("flt_poison", x, average=False)
        out = eng.synchronize(h)
        assert np.isnan(out).all()
        # stalled cycle: the executor call sleeps in place.
        flt.configure("engine.exec:stall:1:0.3")
        t0 = time.monotonic()
        h = eng.allreduce_async("flt_stall", x, average=False)
        eng.synchronize(h)
        assert time.monotonic() - t0 >= 0.29
        # injected executor error: surfaced at synchronize like any
        # organic execution failure.
        flt.configure("engine.exec:error:1")
        h = eng.allreduce_async("flt_err", x, average=False)
        with pytest.raises(EngineError, match="injected fault"):
            eng.synchronize(h)
    finally:
        flt.reset()
        eng.shutdown()


# ---------------------------------------------------------------------------
# checkpoint site + crash-atomic save (satellite regression)
# ---------------------------------------------------------------------------


def test_torn_checkpoint_write_never_becomes_newest(hvd, tmp_path):
    """A rank dying mid-save (ckpt.write:torn) leaves only a tmp file:
    latest_checkpoint keeps pointing at the previous good checkpoint
    and elastic resume loads it cleanly."""
    from horovod_tpu.utils import checkpoint as ckpt

    state = {"w": np.arange(16, dtype=np.float32), "step": 1}
    d = str(tmp_path / "ck")
    good = ckpt.save_checkpoint(d, state, step=1)
    assert good and good.endswith("checkpoint_1.msgpack")

    flt.configure("ckpt.write:torn:1")
    state2 = {"w": np.arange(16, dtype=np.float32) * 2, "step": 2}
    with pytest.raises(flt.FaultInjected, match="injected fault"):
        ckpt.save_checkpoint(d, state2, step=2)
    # The torn write is visible as a tmp — but never as a checkpoint.
    assert os.path.exists(os.path.join(d, "checkpoint_2.msgpack.tmp"))
    assert not os.path.exists(os.path.join(d, "checkpoint_2.msgpack"))
    assert ckpt.latest_checkpoint(d) == good
    restored = ckpt.load_checkpoint(good, dict(state), broadcast=False)
    np.testing.assert_array_equal(restored["w"], state["w"])
    # Disarmed, the interrupted save succeeds and becomes newest.
    flt.reset()
    ckpt.save_checkpoint(d, state2, step=2)
    assert ckpt.latest_checkpoint(d).endswith("checkpoint_2.msgpack")


# ---------------------------------------------------------------------------
# post-mortem attribution: flight dumps carry the injected-fault record
# ---------------------------------------------------------------------------


def test_flight_dumps_attribute_injected_faults(tmp_path, monkeypatch):
    import logging

    from horovod_tpu.core import timeline as tl

    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("HVD_FLIGHT_MIN_INTERVAL", "0")
    flt.configure("kv.set:torn:1")
    flt.kv_set("some/key", "0123456789")
    path = tl.dump_and_warn([], "test: injected-fault dump", 0,
                            logging.getLogger("test"))
    assert path
    payload = json.load(open(path))
    faults = payload.get("faults")
    assert faults, "dump is missing the faults section"
    assert faults["spec"] and "kv.set:torn" in faults["spec"]
    assert any(r["site"] == "kv.set" for r in faults["injected"])

    # Disarmed AND nothing fired -> no faults section at all: an
    # organic incident's post-mortem never hints at injection.
    flt.reset()
    path2 = tl.dump_and_warn([], "test: organic dump", 0,
                             logging.getLogger("test"))
    assert "faults" not in json.load(open(path2))


# ---------------------------------------------------------------------------
# launcher-side scoping (--faults RANK:SPEC parsing; no worlds spawned)
# ---------------------------------------------------------------------------


def test_launcher_faults_flag_parsing():
    from horovod_tpu.run import _parse_faults

    assert _parse_faults(None) == {}
    assert _parse_faults(["1:hb.beat:skip:*"]) == {1: "hb.beat:skip:*"}
    # Repeats for one rank join with commas (the HVD_FAULTS grammar).
    assert _parse_faults(["0:kv.get:delay:2:0.5", "0:kv.set:torn:1"]) \
        == {0: "kv.get:delay:2:0.5,kv.set:torn:1"}
    with pytest.raises(SystemExit):
        _parse_faults(["nope"])
    with pytest.raises(SystemExit):
        _parse_faults(["x:kv.get:delay:1"])
