"""Trainer + callbacks + load_model — the test surface of the reference's
test_keras.py (train-step smoke, callbacks, restore-with-wrapped-optimizer;
reference: test/test_keras.py:41-232)."""

import numpy as np
import optax
import pytest

import horovod_tpu.keras as hvd_keras
from horovod_tpu.keras.callbacks import (
    BroadcastGlobalVariablesCallback,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
)
from horovod_tpu.models import MnistMLP


def _data(n=128, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8, 8, 1).astype(np.float32)
    y = (rng.rand(n) * 10).astype(np.int32) % 10
    # Make labels learnable: label = argmax of a fixed projection.
    w = rng.randn(64, 10).astype(np.float32)
    y = np.argmax(x.reshape(n, -1) @ w, axis=1).astype(np.int32)
    return x, y


def test_trainer_fit_reduces_loss(hvd):
    x, y = _data()
    t = hvd_keras.Trainer(MnistMLP(hidden=32), optax.adam(1e-2))
    hist = t.fit(x, y, batch_size=4, epochs=4,
                 callbacks=[BroadcastGlobalVariablesCallback(0),
                            MetricAverageCallback()])
    assert hist["loss"][-1] < hist["loss"][0]
    assert 0.0 <= hist["accuracy"][-1] <= 1.0


def test_trainer_evaluate_and_predict(hvd):
    x, y = _data(64)
    t = hvd_keras.Trainer(MnistMLP(hidden=16), optax.sgd(0.1))
    t.fit(x, y, batch_size=2, epochs=1)
    logs = t.evaluate(x, y, batch_size=2)
    assert "loss" in logs and "accuracy" in logs
    preds = t.predict(x[:10])
    assert preds.shape == (10, 10)


def test_warmup_callback_scales_lr(hvd):
    x, y = _data(64)
    t = hvd_keras.Trainer(MnistMLP(hidden=16), optax.sgd(0.1, momentum=0.9))
    cb = LearningRateWarmupCallback(warmup_epochs=2, verbose=0)
    hist = t.fit(x, y, batch_size=2, epochs=3, callbacks=[cb])
    # During warmup lr rises toward 1.0 from 1/size; afterwards stays put.
    assert "lr" in hist
    assert hist["lr"][1] >= hist["lr"][0] - 1e-6
    assert abs(hist["lr"][-1] - hist["lr"][1]) < 0.6


def test_schedule_callback_staircase(hvd):
    x, y = _data(64)
    t = hvd_keras.Trainer(MnistMLP(hidden=16), optax.sgd(0.1))
    cb = LearningRateScheduleCallback(
        multiplier=lambda e: 0.1 ** e, start_epoch=0,
        momentum_correction=False)
    hist = t.fit(x, y, batch_size=2, epochs=3, callbacks=[cb])
    np.testing.assert_allclose(hist["lr"], [1.0, 0.1, 0.01], rtol=1e-6)


def test_momentum_correction_scales_trace(hvd):
    x, y = _data(32)
    t = hvd_keras.Trainer(MnistMLP(hidden=16), optax.sgd(0.1, momentum=0.9))
    t.fit(x, y, batch_size=2, epochs=1)
    import jax

    before = [np.array(l) for l in jax.tree_util.tree_leaves(t.opt_state)]
    t.set_lr_scale(2.0, momentum_correction=True)
    after = [np.array(l) for l in jax.tree_util.tree_leaves(t.opt_state)]
    # trace leaves doubled; counts/other leaves unchanged
    changed = sum(not np.allclose(b, a) for b, a in zip(before, after))
    assert changed > 0
    for b, a in zip(before, after):
        assert np.allclose(a, b) or np.allclose(a, 2.0 * b)


def test_save_and_load_model(hvd, tmp_path):
    import horovod_tpu.jax as hvd_jax

    x, y = _data(64)
    t = hvd_keras.Trainer(MnistMLP(hidden=16), optax.adam(1e-2))
    t.fit(x, y, batch_size=2, epochs=2)
    # Multi-controller worlds (the launcher runs this file under -np 2 the
    # way the reference runs its suite under mpirun): save() writes on
    # process 0 only and returns None elsewhere. Its path is valid on
    # every process (single-host launcher => shared FS), and the
    # broadcast doubles as the write->read barrier.
    path = hvd_jax.broadcast_object(t.save(str(tmp_path)))
    assert path is not None
    ref_logs = t.evaluate(x, y, batch_size=2)

    t2 = hvd_keras.load_model(path, MnistMLP(hidden=16), optax.adam(1e-2),
                              x_sample=x[:16])
    logs = t2.evaluate(x, y, batch_size=2)
    assert abs(logs["loss"] - ref_logs["loss"]) < 1e-5
    # Training must continue from the restored wrapped-optimizer state.
    hist = t2.fit(x, y, batch_size=2, epochs=3, initial_epoch=2)
    assert len(hist["loss"]) == 1


def test_bf16_state_trainer_checkpoint_roundtrip(hvd, tmp_path):
    """HBM diet round 2 checkpoint contract: saving a
    state_dtype='bf16' sharded trainer persists the f32 master shards
    (inside the optimizer state), and restore rebuilds the bf16
    residents from them BITWISE — a save->restore->step run continues
    the trajectory."""
    import jax
    import jax.numpy as jnp

    import horovod_tpu.jax as hvd_jax
    from horovod_tpu.jax import fetch, has_master_shards, resident_from_masters

    x, y = _data(64)
    mk = lambda: optax.sgd(0.1, momentum=0.9)
    t = hvd_keras.Trainer(MnistMLP(hidden=16), mk(), sharded_update=True,
                          state_dtype="bf16")
    t.fit(x, y, batch_size=4, epochs=1)
    # Residents live at bf16; the only f32 copy is the master buffers.
    assert all(l.dtype == jnp.bfloat16
               for l in jax.tree_util.tree_leaves(t.params))
    assert has_master_shards(t.opt_state)
    path = hvd_jax.broadcast_object(t.save(str(tmp_path)))
    ref_logs = t.evaluate(x, y, batch_size=4)

    t2 = hvd_keras.load_model(path, MnistMLP(hidden=16), mk(),
                              x_sample=x[:16], sharded_update=True,
                              state_dtype="bf16")
    # Restored residents == cast(master) bitwise (Trainer.load rebuilds
    # them from the persisted masters, not from the saved residents).
    rebuilt = resident_from_masters(t2.opt_state, t2.params)
    for a, b in zip(jax.tree_util.tree_leaves(t2.params),
                    jax.tree_util.tree_leaves(rebuilt)):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))
    # Masters round-trip bitwise. The LIVE masters are P('hvd')-sharded
    # (non-addressable shards under a multi-controller launcher run) —
    # fetch allgathers them; both ranks iterate the same leaf order so
    # the collectives pair up.
    for a, b in zip(jax.tree_util.tree_leaves(t.opt_state["master"]),
                    jax.tree_util.tree_leaves(t2.opt_state["master"])):
        np.testing.assert_array_equal(fetch(a), fetch(b))
    # The restored residents sit within the 1-ulp re-anchor band of the
    # live ones, so the eval loss matches at bf16 resolution...
    logs = t2.evaluate(x, y, batch_size=4)
    assert abs(logs["loss"] - ref_logs["loss"]) < 1e-2
    # ...and training continues (the step runs against the restored
    # mixed-layout state without recomputing a fresh one).
    hist = t2.fit(x, y, batch_size=4, epochs=2, initial_epoch=1)
    assert len(hist["loss"]) == 1 and np.isfinite(hist["loss"][0])


def test_bf16_state_lr_scale_drives_master_trajectory(hvd):
    """The LR warmup/schedule mechanism (set_lr_scale -> the step's
    lr_scale operand) must reach the f32 MASTER trajectory under the
    mixed layout: the masters advance inside opt.update, so the Trainer
    threads the scale into the epilogue instead of scaling the returned
    resident delta (which the next step's re-anchor would undo).
    lr_scale=0 makes the pin exact: one epoch must move nothing."""
    import jax

    from horovod_tpu.jax import fetch

    x, y = _data(64)
    t = hvd_keras.Trainer(MnistMLP(hidden=16), optax.sgd(0.1, momentum=0.9),
                          sharded_update=True, state_dtype="bf16")
    t.build(x[:4])
    t.set_lr_scale(0.0, momentum_correction=False)
    m_before = [fetch(l) for l in
                jax.tree_util.tree_leaves(t.opt_state["master"])]
    p_before = [np.asarray(l, np.float32)
                for l in jax.tree_util.tree_leaves(t.params)]
    t.fit(x, y, batch_size=4, epochs=1)
    for a, b in zip(m_before, [fetch(l) for l in jax.tree_util.tree_leaves(
            t.opt_state["master"])]):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(p_before, [np.asarray(l, np.float32)
                               for l in jax.tree_util.tree_leaves(t.params)]):
        np.testing.assert_array_equal(a, b)
    # ...and a non-zero scale trains (the scale reaches the masters, not
    # a dead code path).
    t.set_lr_scale(1.0, momentum_correction=False)
    t.fit(x, y, batch_size=4, epochs=1)
    assert any(not np.array_equal(a, fetch(b)) for a, b in zip(
        m_before, jax.tree_util.tree_leaves(t.opt_state["master"])))


def test_load_model_rejects_mismatched_checkpoint(hvd, tmp_path):
    """A checkpoint from a DIFFERENT model must be rejected with a
    message naming the mismatched entries — flax from_bytes silently
    restores wrong-shaped leaves, which would otherwise surface steps
    later as a cryptic XLA shape error (r4 verdict weak #4)."""
    import horovod_tpu.jax as hvd_jax

    x, y = _data(32)
    t = hvd_keras.Trainer(MnistMLP(hidden=16), optax.adam(1e-2))
    t.fit(x, y, batch_size=2, epochs=1)
    path = hvd_jax.broadcast_object(t.save(str(tmp_path)))
    with pytest.raises(ValueError, match="does not match"):
        hvd_keras.load_model(path, MnistMLP(hidden=32), optax.adam(1e-2),
                             x_sample=x[:16])


def test_latest_checkpoint(hvd, tmp_path):
    import horovod_tpu.jax as hvd_jax
    from horovod_tpu.utils import latest_checkpoint, save_checkpoint

    # Share process 0's directory (writes happen there only); peers must
    # not probe the empty-dir case on it — process 0 may already have
    # saved by the time they look.
    shared = hvd_jax.broadcast_object(str(tmp_path))
    if hvd.process_index() == 0:
        assert latest_checkpoint(shared) is None
    save_checkpoint(shared, {"a": np.zeros(2)}, step=1)
    save_checkpoint(shared, {"a": np.ones(2)}, step=10)
    hvd_jax.broadcast_object(None)  # write->read barrier for peers
    p = latest_checkpoint(shared)
    assert p is not None and p.endswith("checkpoint_10.msgpack")


def test_metric_average_helper(hvd):
    from horovod_tpu.utils import MetricAverage

    out = MetricAverage({"loss": 2.0, "acc": 0.5})
    assert abs(out["loss"] - 2.0) < 1e-6  # identical on all ranks -> same
    assert MetricAverage({}) == {}


def test_metric_running_average(hvd):
    from horovod_tpu.utils import Metric

    m = Metric("loss")
    assert m.avg == 0.0
    m.update(1.0)
    m.update(3.0)
    assert abs(m.avg - 2.0) < 1e-6


def test_fit_twice_with_full_callback_suite(hvd):
    """The compile-warmup-then-timed-fit pattern every benchmark example
    uses (keras_imagenet_resnet50.py): the SECOND fit re-broadcasts
    state that is now mesh-sharded train-step output. This used to
    recompile the broadcast programs with collectives in flight and
    wedge XLA:CPU's 8-device rendezvous past its 40 s abort (r4, found
    by the smoke tier; broadcast_state now goes host-first). Pinned
    here at unit scale so the regression fails in seconds, not in a
    3-minute example."""
    x, y = _data(64)
    tr = hvd_keras.Trainer(MnistMLP(), optax.sgd(0.05, momentum=0.9))
    cbs = [BroadcastGlobalVariablesCallback(0), MetricAverageCallback(),
           LearningRateWarmupCallback(warmup_epochs=1, verbose=0)]
    h1 = tr.fit(x, y, batch_size=2, epochs=1, callbacks=cbs)
    h2 = tr.fit(x, y, batch_size=2, epochs=2, callbacks=cbs)
    assert "loss" in h1 and len(h2["loss"]) == 2
    # Training continued (state survived the re-broadcast).
    assert h2["loss"][-1] <= h1["loss"][0]
