"""Numerics observatory (ISSUE 8): in-step gradient health, the
`nonfinite` sentinel verdict with the off|warn|halt policy, bf16 drift
gauges, the cross-rank consistency digest, MetricAverage nonfinite
masking, the broadcast non-root masking contract, and the CLI — on the
8-device virtual mesh. The suite-wide default is HVD_NUMERICS=off
(conftest); every test here re-enables the policy explicitly and resets
the module latches.
"""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu.jax as hj
import horovod_tpu.jax.numerics as jnum
from horovod_tpu.common.topology import HVD_AXIS
from horovod_tpu.core import numerics as num
from horovod_tpu.core import sentinel as sentinel
from horovod_tpu.core import telemetry as tele
from horovod_tpu.ops import collectives as C
from horovod_tpu.utils.metrics import MetricAverage


@pytest.fixture(autouse=True)
def _numerics_on(hvd, monkeypatch, tmp_path):
    """warn policy, per-step cadence, a private flight dir, no dump rate
    limit — and clean module latches before AND after (a fired verdict
    must not leak into the next test or into /healthz checks elsewhere)."""
    monkeypatch.setenv("HVD_NUMERICS", "warn")
    monkeypatch.setenv("HVD_NUMERICS_EVERY", "1")
    (tmp_path / "flight").mkdir()
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path / "flight"))
    monkeypatch.setenv("HVD_FLIGHT_MIN_INTERVAL", "0")
    num.reset()
    yield
    num.reset()
    sentinel.reset_sentinel()


def _dumps(tmp_path):
    return sorted(glob.glob(str(tmp_path / "flight" / "hvd_flight.*.json")))


# ---------------------------------------------------------------------------
# Policy / knob parsing
# ---------------------------------------------------------------------------


def test_policy_spellings(monkeypatch):
    for raw, want in (("off", "off"), ("0", "off"), ("false", "off"),
                      ("warn", "warn"), ("1", "warn"), ("on", "warn"),
                      ("halt", "halt"), ("HALT", "halt"),
                      ("bogus", "warn")):
        monkeypatch.setenv("HVD_NUMERICS", raw)
        assert num.policy() == want, raw
    monkeypatch.delenv("HVD_NUMERICS")
    assert num.policy() == "warn"  # production default is warn


def test_check_every_parsing(monkeypatch):
    monkeypatch.delenv("HVD_NUMERICS_EVERY", raising=False)
    assert num.check_every() == 50
    monkeypatch.setenv("HVD_NUMERICS_EVERY", "7")
    assert num.check_every() == 7
    monkeypatch.setenv("HVD_NUMERICS_EVERY", "0")
    assert num.check_every() == 1  # clamped: 0 would divide by zero
    monkeypatch.setenv("HVD_NUMERICS_EVERY", "junk")
    assert num.check_every() == 50


# ---------------------------------------------------------------------------
# Traced building blocks (jax/numerics.py)
# ---------------------------------------------------------------------------


def test_max_ulp_zero_one_and_nan():
    a = jnp.asarray([1.0, -2.5, 0.0], jnp.float32)
    assert int(jnum.max_ulp(a, a)) == 0
    b = jnp.asarray(np.nextafter(np.asarray(a), np.inf))
    assert int(jnum.max_ulp(a, b)) == 1
    r = jnp.asarray([1.0, 1.0], jnp.bfloat16)
    r1 = jnp.asarray([1.0, 1.0 + 2 ** -7], jnp.bfloat16)  # 1 bf16 ulp
    assert int(jnum.max_ulp(r, r1)) == 1
    n = jnp.asarray([1.0, float("nan")], jnp.float32)
    assert int(jnum.max_ulp(a[:2], n)) > 1 << 24  # NaN reads as huge
    with pytest.raises(ValueError):
        jnum.max_ulp(a, r)
    with pytest.raises(ValueError, match="16/32-bit"):
        jnum.max_ulp(np.zeros(2, np.float64), np.zeros(2, np.float64))


def test_guard_updates_is_bitwise_noop_including_signed_zeros():
    params = {"w": jnp.asarray([0.0, -0.0, 1.5, -3.25], jnp.float32),
              "n": jnp.asarray([2, 3], jnp.int32)}
    updates = {"w": jnp.asarray([1.0, 1.0, 1.0, 1.0], jnp.float32),
               "n": jnp.zeros((2,), jnp.int32)}
    skipped = jnum.guard_updates(jnp.asarray(False), updates)
    after = optax.apply_updates(params, skipped)
    for k in params:
        assert (np.asarray(after[k]).tobytes()
                == np.asarray(params[k]).tobytes()), k
    passed = jnum.guard_updates(jnp.asarray(True), updates)
    np.testing.assert_array_equal(np.asarray(passed["w"]),
                                  np.asarray(updates["w"]))


def test_tree_stats_buckets_and_counts():
    tree = {"a": jnp.asarray([1.0, float("nan"), float("inf")],
                             jnp.float32),
            "b": jnp.asarray([3.0, 4.0], jnp.float32),
            "c": jnp.ones((4,), jnp.bfloat16),
            "n": jnp.arange(5, dtype=jnp.int32)}
    stats = jnum.tree_stats(tree)
    assert set(stats) == {"float32", "bfloat16", "int32"}
    assert int(stats["float32"]["nonfinite"]) == 2
    assert int(stats["bfloat16"]["nonfinite"]) == 0
    assert int(stats["int32"]["nonfinite"]) == 0
    # finite sumsq still accumulates the finite bucket exactly
    assert float(stats["bfloat16"]["sumsq"]) == 4.0
    health = jnum.health_of(stats)
    assert int(health["nonfinite"]) == 2
    assert set(health["buckets"]) == set(stats)
    assert not bool(jnum.all_finite(stats))


# ---------------------------------------------------------------------------
# Host intake: verdicts, fire-once, the halt policy (core/numerics.py)
# ---------------------------------------------------------------------------


def _poisoned_health():
    return {
        "grad_norm": float("inf"),
        "nonfinite": 3,
        "buckets": {"float32": {"norm": float("inf"), "nonfinite": 3},
                    "bfloat16": {"norm": 1.0, "nonfinite": 0}},
        "per_rank_nonfinite": np.asarray([0, 0, 3, 0, 0, 0, 0, 0]),
    }


def test_nonfinite_verdict_fires_once_with_attribution(tmp_path):
    num.note_step_health(_poisoned_health(), step=7)
    rep = num.report()
    v = rep["verdicts"]["nonfinite"]
    assert v["step"] == 7
    assert v["buckets"] == {"float32": 3}
    assert v["ranks"] == [2]
    dumps = _dumps(tmp_path)
    assert len(dumps) == 1, dumps
    dump = json.load(open(dumps[0]))
    assert "nonfinite" in dump["reason"] and "step 7" in dump["reason"]
    assert "float32" in dump["reason"] and "[2]" in dump["reason"]
    assert any(ev.get("name") == "NUMERICS_VERDICT"
               for ev in dump["events"])
    # Second poisoned step: counted, NOT re-dumped (fire-once latch).
    before = tele.REGISTRY.counter("numerics.nonfinite.steps").value
    num.note_step_health(_poisoned_health(), step=8)
    assert tele.REGISTRY.counter(
        "numerics.nonfinite.steps").value == before + 1
    assert len(_dumps(tmp_path)) == 1
    assert num.report()["verdicts"]["nonfinite"]["step"] == 7  # first wins


def test_halt_policy_raises_after_dump(tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_NUMERICS", "halt")
    with pytest.raises(num.NonfiniteError) as exc:
        num.note_step_health(_poisoned_health(), step=3)
    assert "step 3" in str(exc.value)
    assert "float32" in str(exc.value)
    assert "NOT applied" in str(exc.value)
    assert len(_dumps(tmp_path)) == 1  # the dump landed before the raise


def test_healthy_step_is_silent(tmp_path):
    health = {"grad_norm": 1.25, "nonfinite": 0,
              "buckets": {"float32": {"norm": 1.25, "nonfinite": 0}}}
    assert num.note_step_health(health, step=1) is None
    assert num.report()["verdicts"] is None
    assert _dumps(tmp_path) == []
    flat = tele.REGISTRY.flat()
    assert flat["numerics.grad_norm"]["last"] == 1.25
    assert flat["numerics.grad_norm.float32"] == 1.25


def test_healthz_degrades_on_numerics_verdict():
    sentinel.reset_sentinel()
    assert sentinel.health()["status"] == "init"
    num.note_step_health(_poisoned_health(), step=11)
    h = sentinel.health()
    assert h["status"] == "warn"
    assert h["verdict"]["verdict"] == "nonfinite"
    assert h["verdict"]["step"] == 11
    assert h["numerics"]["verdicts"] == ["nonfinite"]
    assert h["numerics"]["policy"] == "warn"


# ---------------------------------------------------------------------------
# Cross-rank consistency digest
# ---------------------------------------------------------------------------


def test_params_digest_sees_any_bitwise_change():
    tree = {"w": np.arange(8.0, dtype=np.float32),
            "b": np.ones((3,), np.float32)}
    d1 = num.params_digest(tree)
    d2 = num.params_digest({"w": tree["w"].copy(), "b": tree["b"].copy()})
    assert set(d1) == {"float32"}
    np.testing.assert_array_equal(d1["float32"], d2["float32"])
    flipped = tree["w"].copy()
    flipped[5] = np.nextafter(flipped[5], np.inf)  # 1-ulp flip
    d3 = num.params_digest({"w": flipped, "b": tree["b"]})
    assert tuple(d3["float32"][:2]) != tuple(d1["float32"][:2])  # crc
    # Both crc halves stay exactly representable on the f32 wire (the
    # whole point of splitting the 32-bit crc for the allgather).
    assert all(np.float32(h) == h for h in d3["float32"][:2])
    poisoned = tree["w"].copy()
    poisoned[0] = np.nan
    d4 = num.params_digest({"w": poisoned, "b": tree["b"]})
    assert d4["float32"][3] == 1.0  # nonfinite count rides the digest


def test_compare_digests_names_rank_bucket_process():
    world, names = 8, ["bfloat16", "float32"]
    gathered = np.tile(np.asarray([[1.0, 2.0, 0.0], [3.0, 4.0, 0.0]]),
                       (world, 1, 1))
    ok = num.compare_digests(gathered, names, local_size=4)
    assert ok["ok"] and "mismatch" not in ok
    gathered[5, 1, 0] += 9.0  # rank 5 deviates in the float32 bucket
    bad = num.compare_digests(gathered, names, local_size=4)
    assert not bad["ok"]
    assert bad["mismatch"] == {"float32": [5]}
    assert bad["ranks"] == [5]
    assert bad["processes"] == [1]  # rank 5 // local_size 4
    assert "ambiguous" not in bad  # 7-vs-1 is a strict majority


def test_compare_digests_tie_is_ambiguous_not_rank0_biased():
    """A 2-controller disagreement is a structural 4-vs-4 tie (each
    process's digest is replicated across its local chips): no vote can
    single out the corrupt side, and crowning the first-inserted digest
    would blame the HEALTHY process whenever process 0 is the corrupt
    one. The report must name everyone and say it's ambiguous —
    symmetrically, whichever side differs."""
    world, names = 8, ["float32"]
    for corrupt_proc in (0, 1):
        gathered = np.tile(np.asarray([[1.0, 2.0, 0.0]]), (world, 1, 1))
        lo = corrupt_proc * 4
        gathered[lo:lo + 4, 0, 0] += 7.0
        rep = num.compare_digests(gathered, names, local_size=4)
        assert rep["ok"] is False
        assert rep["ambiguous"] is True
        assert rep["ranks"] == list(range(8))
        assert rep["processes"] == [0, 1], corrupt_proc
    # Three-way splits without a strict majority are ambiguous too.
    gathered = np.tile(np.asarray([[1.0, 2.0, 0.0]]), (world, 1, 1))
    gathered[0:3, 0, 0] += 1.0
    gathered[3:6, 0, 0] += 2.0  # counts {3, 3, 2}: no strict majority
    rep = num.compare_digests(gathered, names, local_size=4)
    assert rep["ok"] is False and rep["ambiguous"] is True


def test_check_consistency_in_lockstep_is_ok(hvd):
    tree = {"w": jnp.arange(16.0, dtype=jnp.float32),
            "s": jnp.ones((4,), jnp.bfloat16)}
    rep = num.check_consistency(tree, tag="unit")
    assert rep["ok"] is True
    assert rep["tag"] == "unit"
    assert set(rep["buckets"]) == {"float32", "bfloat16"}
    assert num.report()["consistency"]["ok"] is True


def test_check_consistency_diverged_verdict(hvd, tmp_path, monkeypatch):
    """A doctored allgather (one chip's digest row off) must yield the
    attributed `diverged` verdict + dump on this process."""
    real_allgather = C.allgather

    def doctored(x):
        out = np.asarray(real_allgather(x))
        out = out.reshape(hvd.size(), -1).copy()
        out[3, 0] += 1.0  # chip 3 reports a different crc
        return out

    monkeypatch.setattr(C, "allgather", doctored)
    rep = num.check_consistency({"w": jnp.ones((8,), jnp.float32)},
                                tag="chaos", step=5)
    assert rep["ok"] is False
    assert rep["ranks"] == [3]
    assert rep["mismatch"] == {"float32": [3]}
    v = num.report()["verdicts"]["diverged"]
    assert v["ranks"] == [3] and v["buckets"] == ["float32"]
    assert v["step"] == 5 and v["tag"] == "chaos"
    dumps = _dumps(tmp_path)
    assert len(dumps) == 1
    assert "diverged" in json.load(open(dumps[0]))["reason"]


# ---------------------------------------------------------------------------
# MetricAverage nonfinite masking (satellite)
# ---------------------------------------------------------------------------


def test_metric_average_excludes_nonfinite(hvd, caplog):
    import logging

    before = tele.REGISTRY.counter("metrics.nonfinite_skipped").value
    with caplog.at_level(logging.WARNING, "horovod_tpu.metrics"):
        out = MetricAverage({"loss": float("nan"), "acc": 0.5,
                             "lr": 0.1})
    # Finite keys are NOT poisoned by the NaN neighbor (the old path
    # shipped them through one stacked allreduce and kept them finite
    # only by luck of element independence; the new path additionally
    # keeps a nonfinite RANK from poisoning the cross-rank average).
    assert out["acc"] == pytest.approx(0.5)
    assert out["lr"] == pytest.approx(0.1)
    # Nonfinite on every rank -> no honest number: stays NaN.
    assert np.isnan(out["loss"])
    assert tele.REGISTRY.counter(
        "metrics.nonfinite_skipped").value == before + 1
    assert any("loss" in r.message for r in caplog.records)


def test_metric_average_all_finite_identity(hvd):
    out = MetricAverage({"a": 1.5, "b": -2.0})
    assert out["a"] == pytest.approx(1.5)
    assert out["b"] == pytest.approx(-2.0)


# ---------------------------------------------------------------------------
# Broadcast non-root masking contract (satellite)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_broadcast_nonroot_nonfinite_never_poisons(hvd, dtype):
    """ops/collectives.py `_root_select_psum`: non-root ranks holding
    NaN/Inf must not leak into the broadcast result (select, not a mask
    multiply — 0 * NaN would be NaN)."""
    n = hvd.size()
    root_row = np.linspace(-2.0, 2.0, 6).astype(np.float32)

    @hj.jit(in_specs=(P(HVD_AXIS, None),), out_specs=P(HVD_AXIS, None))
    def bcast(stack):
        got = hj.broadcast(stack[0], root_rank=0)
        return got[None, :]

    stack = np.tile(root_row, (n, 1))
    stack[1:, ::2] = np.nan  # every non-root rank poisoned with NaN
    stack[1:, 1::2] = np.inf # ... and Inf
    out = np.asarray(bcast(jnp.asarray(stack, dtype)))
    want = np.asarray(jnp.asarray(root_row, dtype), np.float32)
    assert np.isfinite(out).all(), out
    for r in range(n):
        np.testing.assert_array_equal(out[r].astype(np.float32), want,
                                      err_msg=f"rank {r}")


# ---------------------------------------------------------------------------
# Engine hooks (python engine; the native twin rides the 2-proc tier)
# ---------------------------------------------------------------------------


class _IdentityExecutor:
    """Local loopback: the 'reduced' result is the snapshot itself, so a
    poisoned submit yields a poisoned result (the single-rank view of
    the cross-rank failure the 2-proc tier exercises end to end)."""

    def allreduce(self, flat, average):
        return flat

    def allgather(self, t):
        return t

    def broadcast(self, t, root):
        return t.copy()


def _record_engine():
    from horovod_tpu.core import engine as eng
    from horovod_tpu.core import timeline as tl

    return eng.Engine(executor=_IdentityExecutor(), cycle_time_s=0.002,
                      timeline=tl.Timeline(None))


def test_engine_nonfinite_result_verdict(tmp_path):
    e = _record_engine()
    try:
        t = np.ones((4,), np.float32)
        t[2] = np.nan
        h = e.allreduce_async("grad/w", t, average=False)
        e.synchronize(h)  # warn: observe, don't raise
        v = num.report()["verdicts"]["nonfinite"]
        assert v["tensor"] == "grad/w"
        assert v["origin"] == "engine"
        assert v["local_nonfinite_at_submit"] == 1
        flat = tele.REGISTRY.flat()
        assert flat["numerics.engine.nonfinite_submits"] >= 1
        assert flat["numerics.engine.nonfinite_results"] >= 1
        assert len(_dumps(tmp_path)) == 1
    finally:
        e.shutdown()


def test_engine_halt_raises_at_synchronize(monkeypatch):
    monkeypatch.setenv("HVD_NUMERICS", "halt")
    e = _record_engine()
    try:
        t = np.full((3,), np.inf, np.float32)
        h = e.allreduce_async("boom", t, average=False)
        with pytest.raises(num.NonfiniteError, match="boom"):
            e.synchronize(h)
    finally:
        monkeypatch.setenv("HVD_NUMERICS", "off")  # clean engine drain
        e.shutdown()


def test_engine_finite_result_is_silent():
    e = _record_engine()
    try:
        h = e.allreduce_async("ok", np.ones((4,), np.float32),
                              average=False)
        e.synchronize(h)
        assert num.report()["verdicts"] is None
    finally:
        e.shutdown()


# ---------------------------------------------------------------------------
# Trainer integration: NaN at step k on the 8-device mesh (acceptance)
# ---------------------------------------------------------------------------


def _fit_data(n=24, poison_batch=None):
    rng = np.random.RandomState(0)
    x = rng.randn(n, 8, 8, 1).astype(np.float32)
    y = (np.arange(n) % 10).astype(np.int32)
    if poison_batch is not None:
        # Poison the FIRST row of that (global, 8-row) batch: with
        # batch rows sharded P('hvd') in order, row 0 of the batch lands
        # on rank 0 — the per-rank attribution must name exactly that
        # rank. Trainer.fit batch_size is PER CHIP: batch_size=1 on the
        # 8-device mesh makes the global batch 8 rows.
        x[poison_batch * 8] = np.nan
    return x, y


def test_trainer_nan_step_yields_one_attributed_dump(hvd, tmp_path):
    import horovod_tpu.keras as hvd_keras
    from horovod_tpu.models import MnistMLP

    x, y = _fit_data(poison_batch=2)  # NaN enters at step 3 (1-based)
    t = hvd_keras.Trainer(MnistMLP(hidden=8), optax.sgd(0.1))
    t.fit(x, y, batch_size=1, epochs=1, shuffle=False)
    v = num.report()["verdicts"]["nonfinite"]
    assert v["step"] == 3
    assert "float32" in v["buckets"]
    # Only rank 0's local (pre-reduction) gradients were nonfinite: the
    # attribution vector names that rank alone on every rank.
    assert v["ranks"] == [0]
    # Exactly ONE dump: later poisoned steps (warn propagates the NaN)
    # fold into the latch instead of dumping a storm.
    dumps = _dumps(tmp_path)
    assert len(dumps) == 1, dumps
    assert "step 3" in json.load(open(dumps[0]))["reason"]
    assert tele.REGISTRY.counter("numerics.nonfinite.steps").value >= 1


def test_trainer_halt_never_applies_poisoned_update(hvd, tmp_path,
                                                    monkeypatch):
    import horovod_tpu.keras as hvd_keras
    from horovod_tpu.models import MnistMLP

    monkeypatch.setenv("HVD_NUMERICS", "halt")
    x, y = _fit_data(poison_batch=0)  # the FIRST step is poisoned
    t = hvd_keras.Trainer(MnistMLP(hidden=8), optax.sgd(0.1))
    t.build(x[:8])
    snap = jax.tree_util.tree_map(lambda a: np.array(a), t.params)
    with pytest.raises(num.NonfiniteError, match="step 1"):
        t.fit(x, y, batch_size=1, epochs=1, shuffle=False)
    # The poisoned update was provably never applied: params BITWISE
    # unchanged (the in-program guard emitted -0.0 updates and
    # re-selected the optimizer state).
    live = jax.tree_util.tree_map(lambda a: np.asarray(a), t.params)
    for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(snap),
            jax.tree_util.tree_leaves_with_path(live)):
        assert a.tobytes() == b.tobytes(), ka
    assert len(_dumps(tmp_path)) == 1


def test_trainer_fallback_path_guards_and_attributes(hvd, tmp_path,
                                                     monkeypatch):
    """distributed=False Trainer: the optimizer wrapper never runs, so
    the step's FALLBACK health path must (a) psum the stats over the
    rank axis — a NaN on a non-zero rank would otherwise be invisible to
    the host, which only reads device 0's tile of a replicated output —
    and (b) run the halt guard itself, so the 'update was NOT applied'
    claim stays true on this path too."""
    import horovod_tpu.keras as hvd_keras
    from horovod_tpu.models import MnistMLP

    monkeypatch.setenv("HVD_NUMERICS", "halt")
    # Poison a NON-zero rank's row (row 5 of the global batch -> rank 5).
    x, y = _fit_data()
    x[5] = np.nan
    t = hvd_keras.Trainer(MnistMLP(hidden=8), optax.sgd(0.1),
                          distributed=False)
    t.build(x[:8])
    snap = jax.tree_util.tree_map(lambda a: np.array(a), t.params)
    with pytest.raises(num.NonfiniteError, match="step 1"):
        t.fit(x, y, batch_size=1, epochs=1, shuffle=False)
    v = num.report()["verdicts"]["nonfinite"]
    assert v["ranks"] == [5]  # the psum'd per-rank vector names rank 5
    live = jax.tree_util.tree_map(lambda a: np.asarray(a), t.params)
    for a, b in zip(jax.tree_util.tree_leaves(snap),
                    jax.tree_util.tree_leaves(live)):
        assert a.tobytes() == b.tobytes()


def test_trainer_drift_and_update_ratio_gauges(hvd):
    import horovod_tpu.keras as hvd_keras
    from horovod_tpu.models import MnistMLP

    x, y = _fit_data()
    t = hvd_keras.Trainer(MnistMLP(hidden=8), optax.sgd(0.1),
                          sharded_update=True, state_dtype="bf16")
    t.fit(x, y, batch_size=1, epochs=1, shuffle=False)
    flat = tele.REGISTRY.flat()
    # bf16 drift gauge (ulps at the master's magnitude): the
    # re-anchored master path reads stable single digits — the
    # per-step error is bounded by one rounding of that step's delta,
    # never by accumulated history (a real divergence reads tens to
    # thousands; see the direct test).
    assert flat["numerics.drift_ulp.bfloat16"] <= 8
    assert flat["numerics.drift.checks"] >= 1
    # Masterless-caveat gauge inputs ride every checked step.
    assert flat["numerics.update_ratio"] > 0
    drift = num.report()["drift"]
    assert drift is not None and "bfloat16" in drift["ulp"]


def test_drift_ulp_direct_and_perturbed(hvd):
    params = {"w": jnp.linspace(-1.0, 1.0, 33, dtype=jnp.float32
                                ).astype(jnp.bfloat16)}
    opt = hj.DistributedOptimizer(optax.sgd(0.1), sharded_update=True,
                                  state_dtype="bf16")
    state = opt.init(params)
    assert hj.sharded.has_master_shards(state)
    clean = hj.sharded.drift_ulp(state, params)
    assert clean == {"bfloat16": 0}  # init: residents == cast(masters)
    drifted = hj.sharded.drift_ulp(
        state, {"w": (params["w"].astype(jnp.float32) * 1.25
                      ).astype(jnp.bfloat16)})
    assert drifted["bfloat16"] >= 16  # a real divergence reads large
    # NaN residents (a poisoned step the warn policy let through) read
    # as HUGE divergence — never a crash out of the fit loop.
    poisoned = np.asarray(params["w"], np.float32)
    poisoned[3] = np.nan
    nan_drift = hj.sharded.drift_ulp(
        state, {"w": jnp.asarray(poisoned, jnp.bfloat16)})
    assert nan_drift["bfloat16"] >= (1 << 62)
    with pytest.raises(ValueError, match="master"):
        hj.sharded.drift_ulp(optax.sgd(0.1).init(params), params)


# ---------------------------------------------------------------------------
# The off-policy HLO pin (acceptance: the bench headline path)
# ---------------------------------------------------------------------------


def _opt_step_text(monkeypatch, policy: str) -> str:
    """Lower a sharded-update step the way the Trainer builds it: under
    an active policy the stashed in-step health is COLLECTED into the
    step outputs (uncollected tracers would be dead code and XLA would
    prune the instrumentation, hiding the warn-vs-off difference)."""
    monkeypatch.setenv("HVD_NUMERICS", policy)
    params = {"w": jnp.arange(40.0, dtype=jnp.float32)}
    opt = hj.DistributedOptimizer(optax.sgd(0.1, momentum=0.9),
                                  sharded_update=True)
    state = opt.init(params)
    ospec = hj.sharded_state_specs(state)
    num_on = policy != "off"

    @hj.jit(in_specs=(P(), ospec, P()),
            out_specs=(P(), ospec, P()) if num_on else (P(), ospec))
    def step(p, s, g):
        u, s2 = opt.update(g, s, p)
        p2 = optax.apply_updates(p, u)
        if num_on:
            return p2, s2, jnum.collect_traced()
        return p2, s2

    return step.lower(params, state, params).as_text()


def test_off_policy_lowers_zero_instrumentation(hvd, monkeypatch):
    """HVD_NUMERICS=off must lower the sharded-update step with NO
    numerics residue — no is_finite, no attribution all_gather beyond
    the update's own, and the exact op histogram of the uninstrumented
    program (the bench sets off for its headline window; the AOT window
    therefore compiles to the identical HLO as pre-numerics builds)."""
    import re

    off = _opt_step_text(monkeypatch, "off")
    warn = _opt_step_text(monkeypatch, "warn")
    assert "is_finite" not in off
    assert "is_finite" in warn  # the pin is meaningful: warn DOES add it
    # A second off-lowering is byte-identical (no hidden nondeterminism
    # to hide instrumentation behind).
    assert off == _opt_step_text(monkeypatch, "off")

    def ops(txt):
        hist = {}
        for m in re.finditer(r"\bstablehlo\.(\w+)", txt):
            hist[m.group(1)] = hist.get(m.group(1), 0) + 1
        return hist

    hoff, hwarn = ops(off), ops(warn)
    assert hoff != hwarn  # warn adds real ops ...
    assert "is_finite" in hwarn and "is_finite" not in hoff  # ... here


def test_off_policy_trainer_logs_carry_no_numerics(hvd, monkeypatch):
    import horovod_tpu.keras as hvd_keras
    from horovod_tpu.models import MnistMLP

    monkeypatch.setenv("HVD_NUMERICS", "off")
    x, y = _fit_data(n=8)
    before = tele.REGISTRY.counter("numerics.steps.checked").value
    t = hvd_keras.Trainer(MnistMLP(hidden=8), optax.sgd(0.1))
    t.fit(x, y, batch_size=1, epochs=1, shuffle=False)
    # No health was computed, fetched or checked: the compiled step
    # carried no numerics outputs at all under the off policy.
    assert tele.REGISTRY.counter(
        "numerics.steps.checked").value == before


# ---------------------------------------------------------------------------
# Surfaces: hvd.numerics_report, bench compact, the CLI
# ---------------------------------------------------------------------------


def test_top_level_exports(hvd):
    import horovod_tpu as hvd_top

    assert hvd_top.numerics_report()["policy"] == "warn"
    assert hvd_top.NonfiniteError is num.NonfiniteError
    rep = hvd_top.check_consistency({"w": jnp.ones((4,), jnp.float32)})
    assert rep["ok"] is True


def test_compact_shape_for_bench_line():
    c = num.compact()
    assert set(c) == {"policy", "steps_checked", "nonfinite_steps",
                      "grad_norm_last", "consistency_ok", "verdicts"}
    assert c["policy"] == "warn"
    json.dumps(c)  # must be JSON-serializable as-is


def test_cli_file_target_exit_codes(tmp_path, capsys):
    from horovod_tpu.utils import numerics as cli

    healthy = tmp_path / "healthy.prom"
    healthy.write_text("hvd_numerics_steps_checked 12\n"
                       "hvd_engine_submits 4\n"
                       "hvd_numerics_grad_norm_last 1.5\n")
    assert cli.main([str(healthy)]) == 0
    out = capsys.readouterr().out
    assert "hvd_numerics_steps_checked" in out
    assert "hvd_engine_submits" not in out  # numerics filter applies

    sick = tmp_path / "sick.prom"
    sick.write_text("hvd_numerics_nonfinite_events 1\n"
                    "hvd_sentinel_verdict_nonfinite 1\n")
    assert cli.main([str(sick)]) == 3  # scriptable trouble signal
    assert cli.main([str(tmp_path / "missing.prom")]) == 1


def test_cli_json_envelope(tmp_path, capsys):
    from horovod_tpu.utils import numerics as cli

    f = tmp_path / "m.prom"
    f.write_text("hvd_numerics_steps_checked 3\n")
    assert cli.main([str(f), "--json"]) == 0
    env = json.loads(capsys.readouterr().out)
    assert env["source"] == "file" and env["target"] == str(f)
    assert env["samples"] == [{"name": "hvd_numerics_steps_checked",
                               "labels": {}, "value": 3.0}]


def test_cli_live_target(capsys):
    from horovod_tpu.utils import numerics as cli

    assert cli.main(["live"]) == 0
    assert "policy      warn" in capsys.readouterr().out
    num.note_step_health(_poisoned_health(), step=2)
    assert cli.main(["live"]) == 3
    out = capsys.readouterr().out
    assert "nonfinite" in out
