"""Model zoo shape/grad sanity (the reference has no model tests — its
examples are the coverage; here models are first-party so they get real
tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import models


def _init_and_apply(model, x, train=False):
    rng = jax.random.PRNGKey(0)
    variables = model.init({"params": rng, "dropout": rng}, x, train)
    out = model.apply(variables, x, train,
                      rngs={"dropout": rng} if train else None,
                      mutable=["batch_stats"] if train else False)
    return variables, out


def test_mnist_cnn_shapes():
    m = models.MnistConvNet()
    x = jnp.zeros((4, 784))
    _, out = _init_and_apply(m, x)
    assert out.shape == (4, 10)


def test_mnist_mlp_shapes():
    m = models.MnistMLP()
    _, out = _init_and_apply(m, jnp.zeros((2, 28, 28, 1)))
    assert out.shape == (2, 10)


@pytest.mark.parametrize("name,blocks", [("resnet18", 8), ("resnet50", 16)])
def test_resnet_shapes(name, blocks):
    m = models.get_model(name, num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 64, 64, 3))
    variables, out = _init_and_apply(m, x)
    assert out[0].shape == (2, 10) if isinstance(out, tuple) else out.shape == (2, 10)


def test_resnet50_param_count():
    """ResNet-50 ImageNet has ~25.6M params; a structural checksum."""
    m = models.ResNet50(num_classes=1000, dtype=jnp.float32)
    variables = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)),
                      False)
    n = sum(int(np.prod(p.shape)) for p in
            jax.tree_util.tree_leaves(variables["params"]))
    assert 25.4e6 < n < 25.8e6, n


def test_resnet_train_updates_batch_stats():
    m = models.ResNet18(num_classes=10, dtype=jnp.float32)
    x = jnp.ones((2, 32, 32, 3))
    rng = jax.random.PRNGKey(0)
    variables = m.init(rng, x, True)
    out, mutated = m.apply(variables, x, True, mutable=["batch_stats"])
    assert out.shape == (2, 10)
    before = jax.tree_util.tree_leaves(variables["batch_stats"])
    after = jax.tree_util.tree_leaves(mutated["batch_stats"])
    assert any(not np.allclose(b, a) for b, a in zip(before, after))


def test_vgg16_param_count():
    m = models.VGG16(num_classes=1000, dtype=jnp.float32)
    variables = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)),
                      False)
    n = sum(int(np.prod(p.shape)) for p in
            jax.tree_util.tree_leaves(variables["params"]))
    assert 138e6 < n < 139e6, n  # the communication-bound headline model


def test_inception_v3_param_count_and_shape():
    """Inception V3 ImageNet: ~23.8M params (torchvision: 23.83M w/o aux);
    299x299 input -> 8x8 final grid."""
    m = models.InceptionV3(num_classes=1000, dtype=jnp.float32)
    variables = m.init(jax.random.PRNGKey(0),
                       jnp.zeros((1, 299, 299, 3)), False)
    n = sum(int(np.prod(p.shape)) for p in
            jax.tree_util.tree_leaves(variables["params"]))
    assert 23.0e6 < n < 24.5e6, n
    out = m.apply(variables, jnp.zeros((2, 299, 299, 3)), False)
    assert out.shape == (2, 1000)


def test_word2vec_loss_decreases():
    m = models.Word2Vec(vocab_size=100, embedding_dim=16)
    rng = jax.random.PRNGKey(0)
    center = jnp.array([1, 2, 3, 4])
    context = jnp.array([2, 3, 4, 5])
    negs = jax.random.randint(rng, (4, 5), 0, 100)
    variables = m.init(rng, center)

    def loss_fn(params):
        return m.apply({"params": params}, center, context, negs,
                       method=m.neg_loss)

    params = variables["params"]
    l0 = loss_fn(params)
    g = jax.grad(loss_fn)(params)
    params = jax.tree_util.tree_map(lambda p, gr: p - 0.5 * gr, params, g)
    l1 = loss_fn(params)
    assert l1 < l0


def test_transformer_lm_forward_and_grad():
    cfg = models.TransformerConfig(
        vocab_size=128, num_layers=2, num_heads=2, hidden_dim=32,
        mlp_dim=64, max_len=16, dtype=jnp.float32, causal=True)
    m = models.TransformerLM(cfg)
    tokens = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]])
    variables = m.init(jax.random.PRNGKey(0), tokens)
    logits = m.apply(variables, tokens)
    assert logits.shape == (1, 8, 128)

    def loss_fn(params):
        lg = m.apply({"params": params}, tokens)
        tgt = jnp.roll(tokens, -1, axis=1)
        return jnp.mean(
            -jax.nn.log_softmax(lg)[0, jnp.arange(8), tgt[0]])

    g = jax.grad(loss_fn)(variables["params"])
    assert all(np.all(np.isfinite(x)) for x in jax.tree_util.tree_leaves(g))


def test_transformer_causality():
    """Changing a future token must not change past logits."""
    cfg = models.TransformerConfig(
        vocab_size=64, num_layers=1, num_heads=2, hidden_dim=16,
        mlp_dim=32, max_len=8, dtype=jnp.float32, causal=True,
        dropout_rate=0.0)
    m = models.TransformerLM(cfg)
    t1 = jnp.array([[1, 2, 3, 4]])
    t2 = jnp.array([[1, 2, 3, 9]])
    variables = m.init(jax.random.PRNGKey(0), t1)
    l1 = m.apply(variables, t1)
    l2 = m.apply(variables, t2)
    np.testing.assert_allclose(l1[0, :3], l2[0, :3], atol=1e-5)


def test_bert_base_param_count():
    """BERT-base ~110M params (within tolerance; untied LM head adds ~23M)."""
    m = models.BertBase(dtype=jnp.float32, num_layers=2)
    tokens = jnp.zeros((1, 16), jnp.int32)
    variables = m.init(jax.random.PRNGKey(0), tokens)
    n = sum(int(np.prod(p.shape)) for p in
            jax.tree_util.tree_leaves(variables["params"]))
    # 2 layers: embeddings ~23.8M + 2*7.1M + head ~23.5M
    assert 55e6 < n < 75e6, n


def test_transformer_rejects_overlong_sequence():
    cfg = models.TransformerConfig(
        vocab_size=32, num_layers=1, num_heads=2, hidden_dim=16,
        mlp_dim=32, max_len=8, dtype=jnp.float32)
    m = models.TransformerLM(cfg)
    with pytest.raises(ValueError, match="max_len"):
        m.init(jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32))


def test_get_model_unknown():
    with pytest.raises(ValueError):
        models.get_model("alexnet")


def test_space_to_depth_stem_is_exact_reparameterization():
    """The s2d stem computes EXACTLY the classic 7x7/s2 'SAME' conv when
    its 4x4 kernel is derived from the 7x7 weights (the standard TPU
    ResNet stem transform) — same function class, MXU-friendly layout."""
    from jax import lax

    from horovod_tpu.models.resnet import (conv7_kernel_to_s2d,
                                           space_to_depth_2x2)

    rng = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(rng)
    x = jax.random.normal(k1, (2, 16, 16, 3), jnp.float32)
    k7 = jax.random.normal(k2, (7, 7, 3, 8), jnp.float32)

    dn = ("NHWC", "HWIO", "NHWC")
    y_ref = lax.conv_general_dilated(
        x, k7, window_strides=(2, 2), padding=[(2, 3), (2, 3)],
        dimension_numbers=dn)
    y_s2d = lax.conv_general_dilated(
        space_to_depth_2x2(x), conv7_kernel_to_s2d(k7),
        window_strides=(1, 1), padding=[(1, 2), (1, 2)],
        dimension_numbers=dn)
    assert y_s2d.shape == y_ref.shape == (2, 8, 8, 8)
    np.testing.assert_allclose(np.asarray(y_s2d), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)


def test_resnet_remat_policies_bit_exact():
    """Both traffic-removal remat policies (measured NEGATIVE on chip,
    docs/benchmarks.md r5 — kept as opt-ins) are BIT-exact against
    stock autodiff: the recompute is the same deterministic function of
    the same saved values."""
    from functools import partial

    from horovod_tpu.models.resnet import (BottleneckBlock, ResNet,
                                           act_drop_policy,
                                           conv_saves_policy)

    m = ResNet(stage_sizes=[1, 1], block_cls=BottleneckBlock,
               num_classes=10, num_filters=8, dtype=jnp.float32)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 32, 32, 3),
                    jnp.float32)
    v = m.init(jax.random.PRNGKey(0), x, False)

    def loss(params, bs):
        out, mut = m.apply({"params": params, "batch_stats": bs}, x,
                           True, mutable=["batch_stats"])
        return out.sum(), mut["batch_stats"]

    import jax.tree_util as jtu

    (l1, bs1), g1 = jax.value_and_grad(loss, has_aux=True)(
        v["params"], v["batch_stats"])
    for policy in (act_drop_policy(), conv_saves_policy()):
        (l2, bs2), g2 = jax.value_and_grad(
            jax.checkpoint(loss, policy=policy), has_aux=True)(
            v["params"], v["batch_stats"])
        assert float(l1) == float(l2)
        gd = jtu.tree_map(lambda a, b: float(jnp.abs(a - b).max()), g1, g2)
        # Bit-exactness holds on chip (verified r5). XLA:CPU's current
        # jaxlib fuses the rematerialized backward differently from stock
        # autodiff — float32 reassociation noise in the last ulps — so off
        # chip the pin is "same computation to a few ulps", not zero.
        tol = 0.0 if jax.devices()[0].platform == "tpu" else 5e-7
        assert max(jtu.tree_leaves(gd)) <= tol, gd
        bd = jtu.tree_map(lambda a, b: float(jnp.abs(a - b).max()),
                          bs1, bs2)
        assert max(jtu.tree_leaves(bd)) <= tol, bd


def test_inception_s2d_stem_is_exact_reparameterization():
    """The Inception stem's 3x3/s2 'VALID' conv computes EXACTLY as the
    2x2/s1 conv over space-to-depth input when the kernel is derived
    via conv3_kernel_to_s2d — the ResNet stem transform applied to the
    32-channel Inception stem (odd input sizes take one zero pad
    row/col, matching the mapped kernel's zero 4th taps)."""
    from jax import lax

    from horovod_tpu.models.inception import conv3_kernel_to_s2d
    from horovod_tpu.models.resnet import space_to_depth_2x2

    rng = jax.random.PRNGKey(1)
    k1, k2 = jax.random.split(rng)
    # Odd spatial size, like the real 299px input.
    x = jax.random.normal(k1, (2, 15, 15, 3), jnp.float32)
    k3 = jax.random.normal(k2, (3, 3, 3, 8), jnp.float32)

    dn = ("NHWC", "HWIO", "NHWC")
    y_ref = lax.conv_general_dilated(
        x, k3, window_strides=(2, 2), padding="VALID",
        dimension_numbers=dn)
    xp = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))
    y_s2d = lax.conv_general_dilated(
        space_to_depth_2x2(xp), conv3_kernel_to_s2d(k3),
        window_strides=(1, 1), padding="VALID",
        dimension_numbers=dn)
    assert y_s2d.shape == y_ref.shape == (2, 7, 7, 8)
    np.testing.assert_allclose(np.asarray(y_s2d), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)


def test_inception_s2d_stem_trains():
    m = models.get_model("inceptionv3", num_classes=10,
                         dtype=jnp.float32, stem="space_to_depth")
    x = jnp.ones((1, 75, 75, 3), jnp.float32)
    v = m.init(jax.random.PRNGKey(0), x, False)
    out = m.apply(v, x, False)
    assert out.shape == (1, 10)
    with pytest.raises(ValueError):
        models.get_model("inceptionv3", stem="bogus").init(
            jax.random.PRNGKey(0), x, False)


def test_resnet_space_to_depth_stem_trains():
    m = models.get_model("resnet18", num_classes=10, dtype=jnp.float32,
                         stem="space_to_depth")
    x = jnp.zeros((2, 64, 64, 3))
    variables, out = _init_and_apply(m, x)
    logits = out[0] if isinstance(out, tuple) else out
    assert logits.shape == (2, 10)
    k = variables["params"]["conv_init"]["kernel"]
    assert k.shape == (4, 4, 12, 64), k.shape
