"""Fleet observability plane unit tier (core/fleet.py) — merge
arithmetic, quantile-from-buckets, the stale-rank lease, and epoch
scoping, all on in-memory/tmpdir KV backends. The cross-process
behavior (identical instrument vocabularies on both engines, SIGKILL →
STALE without wedging rank 0) lives in tests/test_multiprocess.py."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from horovod_tpu.core import fleet  # noqa: E402
from horovod_tpu.core import telemetry as tele  # noqa: E402
from horovod_tpu.core.coordinator import LocalKV  # noqa: E402

N_BUCKETS = len(tele.LATENCY_BUCKETS_S) + 1


def _hist(**bucket_counts):
    """A snapshot-shaped histogram with counts at named bucket indices
    (``b2=5`` puts 5 observations in bucket index 2)."""
    counts = [0] * N_BUCKETS
    total = 0
    for key, n in bucket_counts.items():
        counts[int(key[1:])] = n
        total += n
    return {"counts": counts, "sum": 0.0, "count": total}


def _snap(rank, seq=1, wall=None, generation=0, epoch=0,
          counters=None, gauges=None, hists=None, rings=None):
    import time

    return {
        "v": 1, "rank": rank, "seq": seq,
        "wall": time.time() if wall is None else wall,
        "generation": generation, "epoch": epoch,
        "counters": counters or {}, "gauges": gauges or {},
        "hists": hists or {}, "rings": rings or {},
        "health": "ok", "numerics": None,
    }


# ---------------------------------------------------------------------------
# Merge arithmetic
# ---------------------------------------------------------------------------

def test_merge_sums_histograms_exactly():
    # Rank 0: all fast (bucket 1); rank 1: a slow tail (bucket 8).
    a = _snap(0, hists={"engine.latency.allreduce": _hist(b1=90)})
    b = _snap(1, hists={"engine.latency.allreduce": _hist(b1=8, b8=2)})
    rep = fleet.merge_snapshots([a, b])
    ar = rep["ops"]["allreduce"]
    assert ar["count"] == 100
    # 98 of 100 observations are <= bucket edge 1 (3e-4 s): the world
    # p50 sits in the fast bucket, the p99 in the tail bucket — exactly
    # what summing the count arrays must produce.
    bounds = list(tele.LATENCY_BUCKETS_S)
    assert ar["p50_us"] <= bounds[1] * 1e6
    assert ar["p99_us"] > bounds[7] * 1e6
    assert ar["p50_us"] <= ar["p99_us"] <= ar["p999_us"]


def test_merge_quantiles_match_quantile_from_buckets():
    a = _snap(0, hists={"engine.latency.broadcast": _hist(b0=3, b5=7)})
    b = _snap(1, hists={"engine.latency.broadcast": _hist(b5=10)})
    rep = fleet.merge_snapshots([a, b])
    bounds = list(tele.LATENCY_BUCKETS_S)
    summed = [x + y for x, y in zip(_hist(b0=3, b5=7)["counts"],
                                    _hist(b5=10)["counts"])]
    want = tele.quantile_from_buckets(bounds, summed, 0.99)
    assert rep["ops"]["broadcast"]["p99_us"] == round(want * 1e6, 1)


def test_merge_skips_foreign_bucket_layouts():
    # A snapshot from a build with different bucket edges must be
    # dropped from the merge, never summed index-by-index.
    a = _snap(0, hists={"engine.latency.allreduce": _hist(b1=10)})
    b = _snap(1, hists={"engine.latency.allreduce": {
        "counts": [5, 5], "sum": 0.0, "count": 10}})
    rep = fleet.merge_snapshots([a, b])
    assert rep["ops"]["allreduce"]["count"] == 10


def test_merge_counter_totals_and_gauge_spreads():
    a = _snap(0, counters={"engine.completed": 10},
              gauges={"engine.queue_depth": 2.0})
    b = _snap(1, counters={"engine.completed": 30},
              gauges={"engine.queue_depth": 6.0})
    rep = fleet.merge_snapshots([a, b])
    assert rep["counters"]["engine.completed"] == 40
    g = rep["gauges"]["engine.queue_depth"]
    assert (g["min"], g["max"], g["mean"]) == (2.0, 6.0, 4.0)
    assert g["per_rank"] == {"0": 2.0, "1": 6.0}
    assert rep["size"] == 2


def test_merge_step_ring_feeds_sparkline_and_heatmap():
    a = _snap(0, rings={"trainer.step_s": [0.01, 0.02, 0.03]})
    b = _snap(1, rings={"trainer.step_s": [0.05]})
    rep = fleet.merge_snapshots([a, b])
    assert rep["step"]["sparkline"] == [0.01, 0.02, 0.03]
    assert rep["step"]["per_rank_last"] == {"0": 0.03, "1": 0.05}
    assert rep["ranks"]["1"]["step_s"] == 0.05


# ---------------------------------------------------------------------------
# Aggregator: lease, liveness, epoch scoping
# ---------------------------------------------------------------------------

def test_aggregator_stale_lease_on_frozen_seq():
    kv = LocalKV({})
    kv.set(fleet.snapshot_key(0, 0, 0), json.dumps(_snap(0, seq=1)))
    kv.set(fleet.snapshot_key(0, 0, 1), json.dumps(_snap(1, seq=1)))
    agg = fleet.FleetAggregator(kv, nproc=2, lease=1.0)
    t = 100.0
    rep = agg.collect(generation=0, epoch=0, now=t)
    assert rep["stale"] == [] and rep["ranks"]["1"]["state"] == "OK"
    # Rank 1's seq freezes; rank 0 keeps beating.
    kv.set(fleet.snapshot_key(0, 0, 0), json.dumps(_snap(0, seq=2)))
    rep = agg.collect(generation=0, epoch=0, now=t + 1.5)
    assert rep["ranks"]["0"]["state"] == "OK"
    assert rep["ranks"]["1"]["state"] == "STALE"
    assert rep["stale"] == [1]
    # The rank comes back: seq advances, marking clears immediately.
    kv.set(fleet.snapshot_key(0, 0, 1), json.dumps(_snap(1, seq=2)))
    rep = agg.collect(generation=0, epoch=0, now=t + 2.0)
    assert rep["stale"] == []


def test_aggregator_within_lease_is_ok():
    kv = LocalKV({})
    kv.set(fleet.snapshot_key(0, 0, 0), json.dumps(_snap(0, seq=1)))
    agg = fleet.FleetAggregator(kv, nproc=1, lease=1.0)
    t = 50.0
    agg.collect(generation=0, epoch=0, now=t)
    rep = agg.collect(generation=0, epoch=0, now=t + 0.5)
    assert rep["ranks"]["0"]["state"] == "OK"


def test_aggregator_never_blocks_on_missing_ranks():
    kv = LocalKV({})
    kv.set(fleet.snapshot_key(0, 0, 2), json.dumps(_snap(2)))
    agg = fleet.FleetAggregator(kv, nproc=8, lease=1.0)
    rep = agg.collect(generation=0, epoch=0, now=0.0)
    assert rep["size"] == 1 and list(rep["ranks"]) == ["2"]


def test_aggregator_epoch_scoping():
    kv = LocalKV({})
    kv.set(fleet.snapshot_key(0, 0, 0), json.dumps(
        _snap(0, epoch=0, counters={"engine.completed": 99})))
    kv.set(fleet.snapshot_key(0, 1, 0), json.dumps(
        _snap(0, epoch=1, counters={"engine.completed": 7})))
    agg = fleet.FleetAggregator(kv, nproc=1, lease=1.0)
    # The new epoch's rollup must not merge against stale-epoch keys.
    rep = agg.collect(generation=0, epoch=1, now=0.0)
    assert rep["counters"]["engine.completed"] == 7
    assert rep["epoch"] == 1


def test_aggregator_extra_snapshot_takes_precedence():
    # Rank 0 hands its registry in directly: the KV copy (older seq)
    # must not shadow it.
    kv = LocalKV({})
    kv.set(fleet.snapshot_key(0, 0, 0), json.dumps(
        _snap(0, seq=1, counters={"engine.completed": 1})))
    agg = fleet.FleetAggregator(kv, nproc=1, lease=1.0)
    local = _snap(0, seq=2, counters={"engine.completed": 5})
    rep = agg.collect(generation=0, epoch=0, now=0.0, extra=[local])
    assert rep["counters"]["engine.completed"] == 5


def test_aggregator_survives_torn_values():
    kv = LocalKV({})
    kv.set(fleet.snapshot_key(0, 0, 0), "{not json")
    kv.set(fleet.snapshot_key(0, 0, 1), json.dumps(_snap(1)))
    agg = fleet.FleetAggregator(kv, nproc=2, lease=1.0)
    rep = agg.collect(generation=0, epoch=0, now=0.0)
    assert rep["size"] == 1


# ---------------------------------------------------------------------------
# Publisher
# ---------------------------------------------------------------------------

def test_publisher_works_without_durable_kwarg():
    # LocalKV.set has no durability knob — the publisher must fall back
    # to the two-argument form rather than require FileKV.
    kv = LocalKV({})
    pub = fleet.FleetPublisher(kv, rank=3, interval=60)
    pub.publish_once()
    raw = kv.try_get(fleet.snapshot_key(*fleet._world_coords(), 3))
    snap = json.loads(raw)
    assert snap["rank"] == 3 and snap["seq"] == 1


def test_publisher_retires_previous_epoch_key(monkeypatch):
    kv = LocalKV({})
    pub = fleet.FleetPublisher(kv, rank=0, interval=60)
    monkeypatch.setattr(fleet, "_world_coords", lambda: (0, 0))
    pub.publish_once()
    assert kv.try_get(fleet.snapshot_key(0, 0, 0)) is not None
    # Elastic shrink: the epoch advances; the dead-epoch key must go.
    monkeypatch.setattr(fleet, "_world_coords", lambda: (0, 1))
    pub.publish_once()
    assert kv.try_get(fleet.snapshot_key(0, 0, 0)) is None
    snap = json.loads(kv.try_get(fleet.snapshot_key(0, 1, 0)))
    assert snap["epoch"] == 1 and snap["seq"] == 2


# ---------------------------------------------------------------------------
# Snapshot vocabulary + cold directory scan (the console path)
# ---------------------------------------------------------------------------

def test_local_snapshot_filters_to_latency_vocabulary():
    tele.REGISTRY.histogram("engine.latency.allreduce").observe(1e-3)
    tele.REGISTRY.histogram("negotiation.fusion_width").observe(4)
    snap = fleet.local_snapshot(rank=0, seq=1, generation=0, epoch=0)
    assert "engine.latency.allreduce" in snap["hists"]
    assert "negotiation.fusion_width" not in snap["hists"]
    counts = snap["hists"]["engine.latency.allreduce"]["counts"]
    assert len(counts) == N_BUCKETS


def test_report_from_dir_picks_newest_epoch_and_marks_stale(tmp_path):
    from horovod_tpu.core.elastic import FileKV

    kv = FileKV(str(tmp_path))
    kv.set(fleet.snapshot_key(0, 0, 0), json.dumps(
        _snap(0, epoch=0, counters={"engine.completed": 99})))
    kv.set(fleet.snapshot_key(0, 1, 0), json.dumps(
        _snap(0, epoch=1, counters={"engine.completed": 3})))
    old = _snap(1, epoch=1)
    old["wall"] -= 3600.0
    kv.set(fleet.snapshot_key(0, 1, 1), json.dumps(old))
    (tmp_path / "not-a-snapshot.txt").write_text("ignore me")
    rep = fleet.report_from_dir(str(tmp_path))
    assert rep["epoch"] == 1
    assert rep["counters"]["engine.completed"] == 3
    assert rep["stale"] == [1]
    assert rep["ranks"]["0"]["state"] == "OK"


def test_report_from_dir_empty_or_missing(tmp_path):
    rep = fleet.report_from_dir(str(tmp_path / "nope"))
    assert rep["size"] == 0
    rep = fleet.report_from_dir(str(tmp_path))
    assert rep["size"] == 0


# ---------------------------------------------------------------------------
# Console rendering
# ---------------------------------------------------------------------------

def test_render_fleet_console():
    from horovod_tpu.utils import stats

    a = _snap(0, hists={"engine.latency.allreduce": _hist(b1=10)},
              rings={"trainer.step_s": [0.01, 0.04, 0.02]},
              counters={"engine.deadline_exceeded": 2})
    b = _snap(1)
    b["wall"] -= 3600.0
    rep = fleet.merge_snapshots([a, b], states={0: "OK", 1: "STALE"})
    out = stats.render_fleet(rep)
    assert "size=2" in out
    assert "STALE" in out
    assert "allreduce" in out
    assert "exceeded=2" in out
    assert "step_s:" in out and "▁" in out  # sparkline rendered


def test_sparkline_shapes():
    from horovod_tpu.utils import stats

    assert stats.sparkline([]) == ""
    assert stats.sparkline([1.0, 1.0]) == "▁▁"
    line = stats.sparkline([0.0, 0.5, 1.0])
    assert line[0] == "▁" and line[-1] == "█"
