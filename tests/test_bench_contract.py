"""CI guards for bench.py's external contract (CLAUDE.md architecture
invariants): `bench.py --help` / `--dry` stay import-free (no jax, no
framework — argparse errors must never pay the multi-second import), and
the one-JSON-line output shape survives refactors. Also pins the
machine-readable `--json` surface of examples/allreduce_benchmark.py at
the argparse level (its full run needs a device world — covered by the
examples smoke tier)."""

import json
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


@pytest.fixture()
def poisoned_env(tmp_path):
    """Environment where importing jax (or the framework package, which
    imports jax) raises immediately — proves a subprocess never touched
    either. The real PYTHONPATH is APPENDED (never replaced: the TPU
    plugin path must survive, CLAUDE.md), with the poison dir first."""
    poison = tmp_path / "poison"
    poison.mkdir()
    (poison / "jax").mkdir()
    (poison / "jax" / "__init__.py").write_text(
        "raise ImportError('bench.py --help/--dry must not import jax')\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = str(poison) + os.pathsep + env.get("PYTHONPATH", "")
    return env


def test_bench_help_is_import_free(poisoned_env):
    proc = subprocess.run([sys.executable, BENCH, "--help"],
                          capture_output=True, text=True, timeout=60,
                          env=poisoned_env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "usage" in proc.stdout.lower()
    assert "must not import jax" not in proc.stderr


def test_bench_argparse_error_is_import_free(poisoned_env):
    proc = subprocess.run([sys.executable, BENCH, "--no-such-flag"],
                          capture_output=True, text=True, timeout=60,
                          env=poisoned_env, cwd=REPO)
    assert proc.returncode == 2  # argparse usage error, not ImportError
    assert "must not import jax" not in proc.stderr


def test_bench_dry_one_json_line_contract(poisoned_env):
    proc = subprocess.run([sys.executable, BENCH, "--dry", "--model",
                           "resnet50", "--batch-size", "32"],
                          capture_output=True, text=True, timeout=60,
                          env=poisoned_env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    # EXACTLY one stdout line, and it is a JSON object (the contract
    # bench.py's consumers — BENCH_r*.json collection — regex for).
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    assert re.match(r"^\{.*\}$", lines[0])
    rec = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "step_time_ms",
                "gflops_per_step", "mfu", "hbm_gb_per_step", "hbm_source",
                "membw_util", "spread_pct", "gate", "state_dtype",
                "compression", "numerics", "dry"):
        assert key in rec, (key, rec)
    assert rec["metric"] == "resnet50_train_images_per_sec_per_chip_bs32"
    assert rec["unit"] == "images/sec/chip"
    assert rec["dry"] is True
    # Numerics observatory (ISSUE 8): the field is present-but-null
    # under --dry (nothing ran, nothing was watched — and the import-free
    # contract above means the observatory was never even imported).
    assert rec["numerics"] is None


def test_bench_dry_check_keeps_contract_and_gate_fields_null(poisoned_env):
    """`--dry --check` (ISSUE 6 satellite): still import-free, still one
    JSON line, the regression-gate fields present-but-null (there is
    nothing to gate without a run), exit 0."""
    proc = subprocess.run([sys.executable, BENCH, "--dry", "--check"],
                          capture_output=True, text=True, timeout=60,
                          env=poisoned_env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "must not import jax" not in proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    rec = json.loads(lines[0])
    assert rec["gate"] is None
    assert rec["spread_pct"] is None
    assert rec["dry"] is True


def test_bench_dry_state_dtype_keeps_contract(poisoned_env):
    """`--state-dtype bf16 --dry` (HBM diet round 2): still import-free,
    still one JSON line, the state_dtype field present-but-null (the
    policy only means something on a real run)."""
    proc = subprocess.run([sys.executable, BENCH, "--dry",
                           "--state-dtype", "bf16"],
                          capture_output=True, text=True, timeout=60,
                          env=poisoned_env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "must not import jax" not in proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    rec = json.loads(lines[0])
    assert rec["state_dtype"] is None
    assert rec["dry"] is True
    # A bad spelling is an argparse error (exit 2), still import-free.
    proc = subprocess.run([sys.executable, BENCH, "--dry",
                           "--state-dtype", "int8"],
                          capture_output=True, text=True, timeout=60,
                          env=poisoned_env, cwd=REPO)
    assert proc.returncode == 2
    assert "must not import jax" not in proc.stderr


def test_bench_dry_compression_keeps_contract(poisoned_env):
    """`--compression int8 --dry` (quantized collectives, ISSUE 12):
    still import-free, still one JSON line, the compression field
    present-but-null (the policy only means something on a real run).
    A bad spelling is an argparse error (exit 2), still import-free."""
    proc = subprocess.run([sys.executable, BENCH, "--dry",
                           "--compression", "int8"],
                          capture_output=True, text=True, timeout=60,
                          env=poisoned_env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "must not import jax" not in proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout
    rec = json.loads(lines[0])
    assert rec["compression"] is None
    assert rec["dry"] is True
    proc = subprocess.run([sys.executable, BENCH, "--dry",
                           "--compression", "int9"],
                          capture_output=True, text=True, timeout=60,
                          env=poisoned_env, cwd=REPO)
    assert proc.returncode == 2
    assert "must not import jax" not in proc.stderr


def test_bench_check_flag_documented():
    proc = subprocess.run([sys.executable, BENCH, "--help"],
                          capture_output=True, text=True, timeout=60,
                          cwd=REPO)
    assert proc.returncode == 0
    assert "--check" in proc.stdout
    assert "--profile" in proc.stdout
    assert "--state-dtype" in proc.stdout
    assert "--compression" in proc.stdout


def test_allreduce_benchmark_has_json_flag():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples",
                                      "allreduce_benchmark.py"), "--help"],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "--json" in proc.stdout
    assert "--decompose" in proc.stdout
    assert "--compression" in proc.stdout
