"""Autotuner: GP regression quality, Bayesian optimization convergence,
ParameterManager tuning loop (reference: parameter_manager.cc,
optim/gaussian_process.cc, optim/bayesian_optimization.cc)."""

import numpy as np
import pytest

from horovod_tpu.tune import (
    BayesianOptimization,
    GaussianProcessRegressor,
    ParameterManager,
)


def test_gp_interpolates_training_points():
    x = np.linspace(0, 1, 8)[:, None]
    y = np.sin(2 * np.pi * x.ravel())
    gp = GaussianProcessRegressor().fit(x, y)
    mu, sd = gp.predict(x)
    np.testing.assert_allclose(mu, y, atol=0.05)
    assert np.all(sd < 0.2)


def test_gp_uncertainty_grows_off_data():
    x = np.array([[0.0], [0.1], [0.2]])
    y = np.array([0.0, 0.1, 0.2])
    gp = GaussianProcessRegressor().fit(x, y)
    _, sd_near = gp.predict(np.array([[0.1]]))
    _, sd_far = gp.predict(np.array([[3.0]]))
    assert sd_far[0] > sd_near[0]


def test_gp_predict_without_fit():
    gp = GaussianProcessRegressor()
    mu, sd = gp.predict(np.array([[0.5]]))
    assert mu.shape == (1,) and sd.shape == (1,)


def test_bayesian_optimization_finds_peak():
    """Maximize -(x-0.3)^2 - (y-0.7)^2 on the unit square."""
    bo = BayesianOptimization([(0.0, 1.0), (0.0, 1.0)], seed=1)

    def f(p):
        return -((p[0] - 0.3) ** 2) - (p[1] - 0.7) ** 2

    for _ in range(25):
        x = bo.next_sample()
        bo.add_sample(x, f(x))
    best = bo.best()
    assert f(best) > -0.02, best


def test_parameter_manager_tunes_and_converges():
    class FakeEngine:
        def __init__(self):
            self.applied = []

        def set_params(self, cycle_time_s=None, fusion_threshold=None):
            self.applied.append((cycle_time_s, fusion_threshold))

    eng = FakeEngine()
    pm = ParameterManager(eng, warmups=1, cycles_per_sample=2,
                          samples_per_step=2, max_steps=4, seed=0)
    # Drive enough cycles: warmup (2 cycles) + 4 steps * 2 samples * 2 cycles
    changes = 0
    for _ in range(2 + 4 * 2 * 2 + 8):
        if pm.update(1 << 20):
            changes += 1
        if not pm.active:
            break
    assert changes >= 2, "never proposed new parameters"
    assert not pm.active, "did not converge"
    # Converged params are inside the reference search space.
    assert 0.0 <= pm.current[0] <= 64.0
    assert 1.0 <= pm.current[1] <= 100.0
    # Applied to the engine: cycle seconds, fusion bytes.
    cyc, fus = eng.applied[-1]
    assert cyc == pytest.approx(pm.current[1] / 1e3)
    assert fus == int(pm.current[0] * 1024 * 1024)


def test_parameter_manager_csv_log(tmp_path):
    class FakeEngine:
        def set_params(self, **kw): ...

    log = tmp_path / "autotune.csv"
    pm = ParameterManager(FakeEngine(), log_path=str(log), warmups=0,
                          cycles_per_sample=1, samples_per_step=1,
                          max_steps=2, seed=0)
    for _ in range(6):
        pm.update(1024)
        if not pm.active:
            break
    pm.close()
    lines = log.read_text().strip().splitlines()
    assert lines[0] == "fusion_mb,cycle_ms,score_bytes_per_us"
    assert len(lines) >= 3  # 2 samples + converged comment


def test_native_engine_set_params_roundtrip():
    from horovod_tpu.core.native_engine import NativeEngine

    class NullExec:
        def allreduce(self, flat, average):
            return flat

        def allgather(self, t):
            return t

        def broadcast(self, t, root):
            return t

    e = NativeEngine(executor=NullExec(), cycle_time_s=0.001)
    try:
        e.set_params(cycle_time_s=0.02, fusion_threshold=123456)
        assert e.cycle_time_s == 0.02
        assert e.fusion_threshold == 123456
        h = e.allreduce_async("x", np.ones(3, np.float32), False)
        e.synchronize(h)
    finally:
        e.shutdown()


def test_native_engine_autotune_ticks(monkeypatch):
    """HVD_AUTOTUNE on the native engine: C++ TICK callbacks must feed the
    ParameterManager once per cycle."""
    import time

    from horovod_tpu.core.native_engine import NativeEngine

    class NullExec:
        def allreduce(self, flat, average):
            return flat

        def allgather(self, t):
            return t

        def broadcast(self, t, root):
            return t

    monkeypatch.setenv("HVD_AUTOTUNE", "1")
    e = NativeEngine(executor=NullExec(), cycle_time_s=0.001)
    try:
        assert e._param_manager is not None
        h = e.allreduce_async("a", np.ones(16, np.float32), False)
        e.synchronize(h)
        deadline = time.monotonic() + 2
        pm = e._param_manager
        while pm._cycle_count == 0 and pm._bytes == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pm._cycle_count > 0 or pm._bytes > 0
    finally:
        e.shutdown()


def test_autotune_env_gate(monkeypatch):
    from horovod_tpu.tune import autotune_enabled

    monkeypatch.delenv("HVD_AUTOTUNE", raising=False)
    monkeypatch.delenv("HOROVOD_AUTOTUNE", raising=False)
    assert not autotune_enabled()
    monkeypatch.setenv("HVD_AUTOTUNE", "1")
    assert autotune_enabled()


def test_autotune_end_to_end_beats_unfused_defaults():
    """C9 exists to make throughput BETTER (VERDICT r2 missing #3): drive
    the real ParameterManager against a deterministic engine cost model
    (1 ms per data-plane call, fusion groups 256x4kB tensors) on a fake
    clock; the tuned params must beat the fusion-off configuration by a
    wide margin and land in the fused region of the search space."""
    import math

    from horovod_tpu.tune import parameter_manager as pmod

    # Injected through the manager's clock seam — patching time.monotonic
    # module-wide would warp live engine/coordinator threads left running
    # by earlier tests in the same process.
    clock = {"t": 0.0}

    state = {"fusion": 0, "cycle_s": 0.001}

    class ModelEngine:
        def set_params(self, cycle_time_s=None, fusion_threshold=None):
            if cycle_time_s:
                state["cycle_s"] = cycle_time_s
            if fusion_threshold is not None:
                state["fusion"] = fusion_threshold

    PER, N, CALL_S = 4096, 256, 0.001

    def run_cycle():
        if state["fusion"] <= 0:
            ncalls = N
        else:
            per_batch = max(1, state["fusion"] // PER)
            ncalls = math.ceil(N / per_batch)
        clock["t"] += state["cycle_s"] + ncalls * CALL_S
        return N * PER

    def throughput():
        t0 = clock["t"]
        b = run_cycle()
        return b / ((clock["t"] - t0) * 1e6)

    # Fusion-off baseline (what HVD_FUSION_THRESHOLD=0 would give).
    state["fusion"], state["cycle_s"] = 0, 0.001
    base = throughput()

    pm = pmod.ParameterManager(ModelEngine(), warmups=1,
                               cycles_per_sample=3, samples_per_step=2,
                               max_steps=8, seed=0,
                               clock=lambda: clock["t"])
    guard = 0
    while pm.active:
        pm.update(run_cycle())
        guard += 1
        assert guard < 10_000
    pm.close()

    tuned = throughput()
    fusion_mb, cycle_ms = pm.current[0], pm.current[1]
    assert fusion_mb * 1024 * 1024 > PER, pm.current  # fused region
    assert tuned > 5 * base, (tuned, base, pm.current)
