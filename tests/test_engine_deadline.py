"""Deadlines, cooperative cancel, and quiesce — the bounded-waiting /
planned-eviction plane of the collective engine (ISSUE 15 tentpole),
pinned for BOTH engines:

- per-request deadlines fail the WAITER with an attributed
  CollectiveTimeout naming the stuck phase (QUEUE / NEGOTIATE_* /
  ALLREDUCE) plus ONE flight dump, while the entry itself may still be
  in flight;
- cancel() retires pre-announce entries locally and discards the result
  of already-announced/executing ones (CancelledError either way);
- quiesce() closes admission with a descriptive error, drains in-flight
  work within a deadline, reports what drained, and flips /healthz to
  ``draining``;
- no deadline set = zero new hot-path work (the sweep short-circuits).
"""

import glob
import json
import os
import threading
import time

import numpy as np
import pytest

from horovod_tpu.core import engine as eng
from horovod_tpu.core import telemetry as tele
from horovod_tpu.core import timeline as tl
from horovod_tpu.core.native_engine import NativeEngine


class GatedExecutor:
    """Local data plane whose allreduce can be held open (the wedged-
    collective stand-in the deadline plane exists for)."""

    measure_staging = False
    last_stage_s = 0.0
    pool = None
    wire_policy = "none"
    last_wire_bytes = 0
    last_wire_compressed = 0

    def __init__(self, world=8):
        self.world = world
        self.gate = threading.Event()
        self.gate.set()  # open by default; tests close it to wedge
        self.calls = []

    def allreduce(self, flat, average):
        self.calls.append(flat.size)
        assert self.gate.wait(10.0), "executor gate never released"
        return flat if average else flat * self.world

    def allgather(self, t):
        return np.tile(t, (self.world,) + (1,) * (t.ndim - 1))

    def broadcast(self, t, root):
        return t.copy()


def _mk_py(executor=None, **kw):
    kw.setdefault("cycle_time_s", 0.002)
    kw.setdefault("stall_warning_s", 0.2)
    kw.setdefault("timeline", tl.Timeline(None))
    return eng.Engine(executor=executor or GatedExecutor(), **kw)


def _mk_native(executor=None, **kw):
    kw.setdefault("cycle_time_s", 0.002)
    kw.setdefault("stall_warning_s", 0.2)
    kw.setdefault("timeline_path", "")
    return NativeEngine(executor=executor or GatedExecutor(), **kw)


ENGINES = [("python", _mk_py), ("native", _mk_native)]


# ---------------------------------------------------------------------------
# deadline expiry per phase
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_deadline_fails_waiter_in_exec_phase(impl, mk, tmp_path,
                                             monkeypatch):
    """An entry wedged INSIDE the executor call: the watchdog-side sweep
    fails the waiter promptly with the op-phase attribution, one flight
    dump lands, and the late completion is discarded (not delivered)."""
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("HVD_FLIGHT_MIN_INTERVAL", "0")
    ex = GatedExecutor()
    ex.gate.clear()  # wedge the collective
    e = mk(ex)
    try:
        before = tele.REGISTRY.counter("engine.deadline_exceeded").value
        h = e.allreduce_async("wedge", np.ones(8, np.float32), False,
                              deadline_ms=150)
        t0 = time.monotonic()
        with pytest.raises(eng.CollectiveTimeout) as ei:
            e.synchronize(h)
        took = time.monotonic() - t0
        assert took < 5.0, took  # failed fast, not the stall horizon
        msg = str(ei.value)
        assert "wedge" in msg and "ALLREDUCE" in msg, msg
        assert "exceeded its deadline" in msg
        assert tele.REGISTRY.counter(
            "engine.deadline_exceeded").value == before + 1
        # ONE attributed flight dump names the stuck phase (written by
        # the sweep thread right after it wakes the waiter — poll).
        deadline = time.monotonic() + 3.0
        mine = []
        while not mine and time.monotonic() < deadline:
            dumps = []
            for path in glob.glob(os.path.join(str(tmp_path), "*.json")):
                try:
                    dumps.append(json.load(open(path)))
                except (OSError, ValueError):
                    continue
            mine = [d for d in dumps if "deadline" in d.get("reason", "")]
            if not mine:
                time.sleep(0.02)
        assert len(mine) == 1, [d.get("reason") for d in dumps]
        assert "ALLREDUCE" in mine[0]["reason"] or \
            "wedge" in mine[0]["reason"], mine[0]["reason"]
    finally:
        ex.gate.set()
        time.sleep(0.05)  # let the late completion retire the entry
        e.shutdown()


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_deadline_fires_under_default_watchdog_cadence(impl, mk):
    """Regression: with the DEFAULT stall cadence (60 s -> 12 s watchdog
    tick) the tightened sweep tick alone only takes effect on the NEXT
    watchdog wait — a deadline'd submit must KICK the watchdog out of an
    already-started coarse sleep, or an exec-wedged request waits out
    the executor instead of its deadline. Found by driving the default
    config; the other tests mask it with stall_warning_s=0.2."""
    ex = GatedExecutor()
    ex.gate.clear()  # wedge the collective
    e = mk(ex, stall_warning_s=60.0)
    try:
        # Let the watchdog settle into its coarse (12 s) sleep first.
        time.sleep(0.3)
        h = e.allreduce_async("kick", np.ones(8, np.float32), False,
                              deadline_ms=150)
        t0 = time.monotonic()
        with pytest.raises(eng.CollectiveTimeout):
            e.synchronize(h)
        took = time.monotonic() - t0
        assert took < 2.0, took  # kicked awake, not the 12 s tick
    finally:
        ex.gate.set()
        time.sleep(0.05)
        e.shutdown()


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_deadline_fails_waiter_in_queue_phase(impl, mk):
    """An entry stuck behind a wedged cycle, never executed: QUEUE-phase
    attribution (the loop thread is busy, the watchdog sweep fires)."""
    ex = GatedExecutor()
    e = mk(ex)
    try:
        ex.gate.clear()
        h_plug = e.allreduce_async("plug", np.ones(4, np.float32), False)
        time.sleep(0.05)  # plug is inside the executor; queue is wedged
        h = e.allreduce_async("queued", np.ones(4, np.float32), False,
                              deadline_ms=120)
        with pytest.raises(eng.CollectiveTimeout, match="QUEUE"):
            e.synchronize(h)
        ex.gate.set()
        np.testing.assert_allclose(e.synchronize(h_plug),
                                   np.full(4, 8.0))
    finally:
        ex.gate.set()
        e.shutdown()


def test_deadline_negotiate_phase_python_engine():
    """Multi-controller attribution: an entry announced to a coordinator
    that never resolves it is stuck in NEGOTIATE_* — the per-cycle sweep
    names the phase (python engine; the native twin shares the literal
    via the parity-checked span vocabulary)."""
    from horovod_tpu.core import coordinator as coord

    class StallingCoord:
        clock_ready = False
        last_tables = None
        cycle_time_s = 0.002
        fusion_threshold = 1 << 26
        waiting_on = None
        dead = None

        def negotiate(self, metas):
            # Peers never agree: nothing resolves, nothing errors.
            return coord.Decision(groups=[])

        def missing_processes(self, name):
            return []

        def close(self):
            pass

    ex = GatedExecutor()
    e = _mk_py(ex)
    try:
        e._coordinator = StallingCoord()
        h = e.allreduce_async("negotiating", np.ones(4, np.float32),
                              False, deadline_ms=120)
        with pytest.raises(eng.CollectiveTimeout,
                           match="NEGOTIATE_ALLREDUCE"):
            e.synchronize(h)
    finally:
        e._coordinator = None
        e.shutdown()


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_env_default_deadline(impl, mk, monkeypatch):
    """HVD_COLLECTIVE_DEADLINE_S arms every request; per-request
    deadline_ms <= 0 opts a single request back out."""
    monkeypatch.setenv("HVD_COLLECTIVE_DEADLINE_S", "0.15")
    ex = GatedExecutor()
    ex.gate.clear()
    e = mk(ex)
    try:
        assert e.default_deadline_s == pytest.approx(0.15)
        h = e.allreduce_async("defaulted", np.ones(4, np.float32), False)
        with pytest.raises(eng.CollectiveTimeout):
            e.synchronize(h)
    finally:
        ex.gate.set()
        time.sleep(0.05)
        e.shutdown()


def test_bad_deadline_env_fails_fast(monkeypatch):
    monkeypatch.setenv("HVD_COLLECTIVE_DEADLINE_S", "soon")
    with pytest.raises(eng.EngineError, match="HVD_COLLECTIVE_DEADLINE_S"):
        eng.collective_deadline_from_env()


def test_no_deadline_means_no_sweep_work():
    """The acceptance's zero-new-hot-path-work clause: with no deadline
    armed, the sweep is a counter check and nothing else."""
    e = _mk_py()
    try:
        assert e._deadline_count == 0
        h = e.allreduce_async("plain", np.ones(4, np.float32), False)
        assert e._deadline_count == 0
        e.synchronize(h)
        before = tele.REGISTRY.counter("engine.deadline_exceeded").value
        e._sweep_deadlines()  # must be a no-op
        assert tele.REGISTRY.counter(
            "engine.deadline_exceeded").value == before
    finally:
        e.shutdown()


# ---------------------------------------------------------------------------
# cooperative cancel
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_cancel_before_execution_retires_locally(impl, mk):
    """A cancel that lands while the entry is still queued: the entry
    never reaches the executor; synchronize raises CancelledError and
    engine.cancelled counts it."""
    ex = GatedExecutor()
    e = mk(ex)
    try:
        before = tele.REGISTRY.counter("engine.cancelled").value
        ex.gate.clear()
        h_plug = e.allreduce_async("plug", np.ones(4, np.float32), False)
        time.sleep(0.05)
        h = e.allreduce_async("victim", np.ones(4, np.float32), False)
        assert e.cancel(h) is True
        ex.gate.set()
        with pytest.raises(eng.CancelledError, match="victim"):
            e.synchronize(h)
        e.synchronize(h_plug)
        # The victim never executed (only the plug hit the data plane).
        assert len(ex.calls) == 1, ex.calls
        if hasattr(e, "_collect_stats"):
            e._collect_stats()  # native: fold the C++ counters in
        assert tele.REGISTRY.counter(
            "engine.cancelled").value == before + 1
        # The name is free again after the cancelled retirement.
        h2 = e.allreduce_async("victim", np.ones(4, np.float32), False)
        np.testing.assert_allclose(e.synchronize(h2), np.full(4, 8.0))
    finally:
        ex.gate.set()
        e.shutdown()


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_cancel_mid_execution_completes_and_discards(impl, mk):
    """A cancel AFTER the entry reached the executor (the post-agreement
    shape: a fused/negotiated batch cannot be torn): execution completes
    cross-rank, the result is discarded, the waiter sees
    CancelledError."""
    ex = GatedExecutor()
    e = mk(ex)
    try:
        ex.gate.clear()
        h = e.allreduce_async("midflight", np.ones(4, np.float32), False)
        deadline = time.monotonic() + 5
        while not ex.calls:  # wait until it is INSIDE the executor
            assert time.monotonic() < deadline
            time.sleep(0.002)
        assert e.cancel(h) is True
        ex.gate.set()  # the collective completes...
        with pytest.raises(eng.CancelledError):  # ...and is discarded
            e.synchronize(h)
        assert len(ex.calls) == 1  # it DID execute (coherence preserved)
    finally:
        ex.gate.set()
        e.shutdown()


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_cancel_unknown_or_done_returns_false(impl, mk):
    e = mk()
    try:
        h = e.allreduce_async("done", np.ones(2, np.float32), False)
        e.synchronize(h)
        assert e.cancel(h) is False
        assert e.cancel(10_000) is False
    finally:
        e.shutdown()


# ---------------------------------------------------------------------------
# quiesce (admission close + bounded drain)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_quiesce_drains_and_closes_admission(impl, mk):
    from horovod_tpu.core import sentinel

    ex = GatedExecutor()
    e = mk(ex)
    try:
        hs = [e.allreduce_async(f"drain/{i}", np.ones(4, np.float32),
                                False) for i in range(3)]
        report = e.quiesce(2.0, reason="test drain")
        assert report["deadline_hit"] is False
        # Everything in flight completed...
        for h in hs:
            np.testing.assert_allclose(e.synchronize(h), np.full(4, 8.0))
        # ...and new work fails fast with the descriptive error.
        with pytest.raises(eng.EngineError, match="draining.*quiesce"):
            e.allreduce_async("late", np.ones(2, np.float32), False)
        # /healthz reports draining (non-200 at the endpoint).
        h = sentinel.health()
        assert h["status"] == "draining"
        assert "test drain" in h["draining"]
    finally:
        sentinel.note_draining(None)
        e.shutdown()


@pytest.mark.parametrize("impl,mk", ENGINES)
def test_quiesce_deadline_reports_wedged_work(impl, mk):
    """Work wedged behind a dead peer cannot be drained — the report
    NAMES it instead of hanging (both engines: the report shape — name
    lists, not counts — is part of the same-observable-semantics
    contract; the native binding projects the names off the inspect
    table, ``hvd_engine_inspect``)."""
    from horovod_tpu.core import sentinel

    ex = GatedExecutor()
    ex.gate.clear()
    e = mk(ex)
    try:
        e.allreduce_async("wedged", np.ones(4, np.float32), False)
        time.sleep(0.03)
        t0 = time.monotonic()
        report = e.quiesce(0.3, reason="bounded")
        assert time.monotonic() - t0 < 2.0
        assert report["deadline_hit"] is True
        assert "wedged" in report["still_pending"]
        assert report["drained"] == []
    finally:
        sentinel.note_draining(None)
        ex.gate.set()
        e.shutdown()


def test_quiesce_engine_module_helper_without_engine():
    """The module-level helper is a no-op when no engine singleton was
    ever built (the elastic-shrink call site must never build one just
    to drain it)."""
    assert eng._engine is None or True  # document intent
    # Force-check the None path against a private copy of the global.
    saved = eng._engine
    try:
        eng._engine = None
        assert eng.quiesce_engine(0.1) is None
    finally:
        eng._engine = saved


# ---------------------------------------------------------------------------
# timeline/flight surface
# ---------------------------------------------------------------------------


def test_cancel_and_deadline_events_in_ring():
    """The CANCELLED span and the DEADLINE_EXCEEDED instant (with phase
    args) land in the flight-recorder ring — the post-mortem surface the
    parity checker pins across both writers."""
    ex = GatedExecutor()
    e = _mk_py(ex)
    try:
        ex.gate.clear()
        h_plug = e.allreduce_async("plug", np.ones(4, np.float32), False)
        time.sleep(0.05)
        h = e.allreduce_async("victim", np.ones(4, np.float32), False)
        e.cancel(h)
        hd = e.allreduce_async("overdue", np.ones(4, np.float32), False,
                               deadline_ms=80)
        with pytest.raises(eng.CollectiveTimeout):
            e.synchronize(hd)
        ex.gate.set()
        with pytest.raises(eng.CancelledError):
            e.synchronize(h)
        e.synchronize(h_plug)
        events = e.timeline.recent()
        names = {ev.get("name") for ev in events}
        assert tl.CANCELLED in names, sorted(names)
        dl = [ev for ev in events
              if ev.get("name") == tl.DEADLINE_EXCEEDED]
        assert dl and "phase" in dl[0].get("args", {}), dl
    finally:
        ex.gate.set()
        e.shutdown()


def test_native_ring_carries_deadline_instant(tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_FLIGHT_DIR", str(tmp_path))
    ex = GatedExecutor()
    ex.gate.clear()
    e = _mk_native(ex)
    try:
        h = e.allreduce_async("overdue", np.ones(4, np.float32), False,
                              deadline_ms=80)
        with pytest.raises(eng.CollectiveTimeout):
            e.synchronize(h)
        events = e.recent_events()
        dl = [ev for ev in events
              if ev.get("name") == "DEADLINE_EXCEEDED"]
        assert dl and dl[0].get("args", {}).get("phase"), events[-5:]
    finally:
        ex.gate.set()
        time.sleep(0.05)
        e.shutdown()
