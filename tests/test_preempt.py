"""Graceful preemption (ISSUE 15): SIGTERM → drain → crash-atomic
emergency checkpoint → drain barrier → exit 0 → resume.

Tiers in this file:

- unit: the preempt request plane (signal-free ``request()``, the
  deterministic ``preempt.signal`` faultline site, ``bounded`` deadline
  aborts, the drain barrier's timeout fallback);
- launcher: ``run.py`` SIGTERM forwarding — children get ``--grace-s``
  to exit clean, stragglers are escalated to SIGKILL, and the report
  says which was which;
- ``chaos`` marker: the full ladder for BOTH engines — a 2-process
  training world preempts mid-epoch (deterministic fault site), every
  rank exits 0 with a checkpoint + journaled note, and a relaunch
  resumes with a continuous loss curve.
"""

import glob
import json
import math
import os
import signal
import subprocess
import sys
import time

import pytest

from horovod_tpu.core import faultline as flt
from horovod_tpu.core import preempt

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WORKER = os.path.join(_REPO, "tests", "preempt_worker.py")


@pytest.fixture(autouse=True)
def _clean_preempt_state():
    preempt.reset()
    flt.reset()
    yield
    preempt.reset()
    flt.reset()


# ---------------------------------------------------------------------------
# units: request plane
# ---------------------------------------------------------------------------


def test_request_and_reset():
    assert preempt.requested() is False
    preempt.request("test eviction")
    assert preempt.requested() is True
    assert preempt.reason() == "test eviction"
    preempt.reset()
    assert preempt.requested() is False


def test_faultline_site_delivers_deterministically():
    """preempt.signal:deliver:1@3 — the third poll 'receives SIGTERM';
    the request then LATCHES (one firing preempts the whole run)."""
    flt.configure("preempt.signal:deliver:1@3")
    assert preempt.requested() is False
    assert preempt.requested() is False
    assert preempt.requested() is True
    assert preempt.requested() is True  # latched
    assert "preempt.signal" in (preempt.reason() or "")


def test_bounded_deadline_aborts_wedged_rung():
    import threading

    release = threading.Event()
    t0 = time.monotonic()
    ok, _ = preempt.bounded(lambda: release.wait(30), 0.2, "wedged rung")
    assert ok is False
    assert time.monotonic() - t0 < 2.0
    release.set()
    ok, val = preempt.bounded(lambda: 42, 1.0, "fast rung")
    assert ok is True and val == 42


def test_drain_barrier_single_process_is_trivial(hvd):
    assert preempt.drain_barrier(0.1) is True


def test_drain_barrier_timeout_fallback(hvd, tmp_path, monkeypatch):
    """A peer that never reaches the barrier (dead, or never preempted)
    must not wedge the exit: the rendezvous times out and returns False
    — exit anyway."""
    from horovod_tpu.common import topology as topo

    monkeypatch.setenv("HVD_ELASTIC_DIR", str(tmp_path))
    monkeypatch.setattr(topo, "num_processes", lambda: 2)
    monkeypatch.setattr(topo, "process_index", lambda: 0)
    t0 = time.monotonic()
    assert preempt.drain_barrier(0.3) is False
    assert time.monotonic() - t0 < 3.0
    # Our own mark landed on the file plane for the (absent) peer.
    marks = os.listdir(tmp_path / "kv")
    assert any("preempt" in m and "p0" in m for m in marks), marks
    # With the peer's mark present, the same barrier passes.
    from horovod_tpu.core.elastic import FileKV

    FileKV(str(tmp_path / "kv")).set("hvd/preempt/g0/p1", "1.0")
    assert preempt.drain_barrier(2.0) is True


def test_journal_note_written(tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_PREEMPT_DIR", str(tmp_path))
    preempt.request("maintenance")
    path = preempt.journal_note(epoch=3, checkpoint="ckpt_3")
    assert path is not None
    rec = json.load(open(path))
    assert rec["kind"] == "preempted"
    assert rec["reason"] == "maintenance"
    assert rec["epoch"] == 3 and rec["checkpoint"] == "ckpt_3"


# ---------------------------------------------------------------------------
# launcher: SIGTERM forwarding + grace escalation
# ---------------------------------------------------------------------------


def _clean_env(extra=None):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra or {})
    return env


def _launch_and_sigterm(child_script, grace_s, settle_s=2.0):
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.run", "-np", "2",
         "--grace-s", str(grace_s), "--",
         sys.executable, "-c", child_script],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=_clean_env(), cwd=_REPO)
    time.sleep(settle_s)  # children spawned (plain python, no jax)
    proc.send_signal(signal.SIGTERM)
    out, err = proc.communicate(timeout=120)
    return proc.returncode, out, err


def test_launcher_sigterm_forwards_and_reports_clean_drain():
    """Satellite: SIGTERM no longer tears the world down immediately —
    it is forwarded, children drain within --grace-s, the report names
    the clean exits, and a fully-clean drain exits 0."""
    child = ("import signal, sys, time\n"
             "def bye(s, f):\n"
             "    print('child drained clean', flush=True)\n"
             "    sys.exit(0)\n"
             "signal.signal(signal.SIGTERM, bye)\n"
             "time.sleep(120)\n")
    rc, out, err = _launch_and_sigterm(child, grace_s=20)
    assert rc == 0, (rc, err[-2000:])
    assert "SIGTERM received: forwarding to 2 child(ren)" in err, \
        err[-2000:]
    assert err.count("exited clean during the drain") == 2, err[-2000:]
    assert "2 clean, 0 escalated" in err, err[-2000:]


def test_launcher_sigterm_escalates_stragglers():
    """A child that ignores SIGTERM is SIGKILLed only after --grace-s,
    and the report says it was escalated."""
    child = ("import os, signal, sys, time\n"
             "if os.environ['HVD_PROCESS_ID'] == '1':\n"
             "    signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
             "else:\n"
             "    signal.signal(signal.SIGTERM,\n"
             "                  lambda s, f: sys.exit(0))\n"
             "time.sleep(120)\n")
    rc, out, err = _launch_and_sigterm(child, grace_s=2)
    assert rc == 128 + signal.SIGTERM, (rc, err[-2000:])
    assert "rank 1" in err and "escalating to SIGKILL" in err, err[-2000:]
    assert "1 clean, 1 escalated" in err, err[-2000:]


# ---------------------------------------------------------------------------
# chaos: the full ladder, both engines, with a resumed relaunch
# ---------------------------------------------------------------------------

ENGINES = ["native", "python"]


def _run_world(edir, engine, faults, epochs):
    cmd = [sys.executable, "-m", "horovod_tpu.run", "-np", "2", "--cpu",
           "--grace-s", "60"]
    for f in faults:
        cmd += ["--faults", f]
    cmd += ["--", sys.executable, _WORKER]
    env = _clean_env({
        "HVD_ENGINE": engine,
        "HVD_PREEMPT_TEST_DIR": edir,
        "HVD_PREEMPT_DIR": edir,
        "HVD_CHECKPOINT_DIR": os.path.join(edir, "ckpt"),
        "HVD_TEST_EPOCHS": str(epochs),
        "HVD_PREEMPT_BARRIER_S": "30",
        "HVD_FLIGHT_DIR": os.path.join(edir, "flight"),
    })
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=420, env=env, cwd=_REPO)


def _losses(edir, rank):
    path = os.path.join(edir, f"losses.rank{rank}.jsonl")
    with open(path) as fh:
        return [json.loads(ln) for ln in fh if ln.strip()]


@pytest.mark.chaos
@pytest.mark.parametrize("engine", ENGINES)
def test_chaos_preemption_drain_checkpoint_resume(engine, tmp_path):
    """ISSUE 15 acceptance, both engines: a deterministic 'SIGTERM'
    (the preempt.signal faultline site, armed identically on both
    ranks) lands mid-epoch-1. Every rank must drain the step, write the
    emergency checkpoint, journal a ``preempted`` note, and exit 0; the
    relaunch resumes from that checkpoint with a continuous loss curve
    (no restart-from-scratch jump)."""
    edir = str(tmp_path / f"preempt_{engine}")
    os.makedirs(edir)
    # The requested() poll runs once per batch; 16 batches/epoch at
    # these shapes, so @24 fires at epoch 1, batch ~7 on BOTH ranks.
    spec = "preempt.signal:deliver:1@24"
    proc = _run_world(edir, engine,
                      faults=[f"0:{spec}", f"1:{spec}"], epochs=6)
    out, err = proc.stdout, proc.stderr
    assert proc.returncode == 0, (proc.returncode, out[-4000:],
                                  err[-3000:])
    # Both ranks walked the ladder and exited 0.
    assert "PREEMPTED rank=0" in out and "PREEMPTED rank=1" in out, \
        out[-4000:]
    assert "ckpt=yes" in out, out[-4000:]
    assert "PREEMPT_TEST DONE" not in out  # evicted, not finished
    # Crash-atomic emergency checkpoint on disk.
    ckpts = glob.glob(os.path.join(edir, "ckpt", "checkpoint_*.msgpack"))
    assert ckpts, os.listdir(edir)
    # Journaled 'preempted' notes for both ranks, naming the injected
    # signal and the checkpoint.
    for rank in (0, 1):
        note = json.load(open(os.path.join(edir, "preempt",
                                           f"p{rank}.json")))
        assert note["kind"] == "preempted", note
        assert "preempt.signal" in note["reason"], note
        assert note["barrier_ok"] is True, note
    # The relaunch resumes from the emergency checkpoint and finishes.
    proc2 = _run_world(edir, engine, faults=[], epochs=6)
    out2 = proc2.stdout
    assert proc2.returncode == 0, (proc2.returncode, out2[-4000:],
                                   proc2.stderr[-3000:])
    assert "RESUMED rank=0 at epoch 2" in out2, out2[-3000:]
    assert out2.count("PREEMPT_TEST DONE") == 2, out2[-3000:]
    # Loss continuity across the eviction: epochs 0..1 from phase 1 +
    # 2..5 from phase 2, finite, no restart-from-scratch jump, net
    # progress end to end.
    recs = _losses(edir, 0)
    epochs_seen = [r["epoch"] for r in recs]
    assert epochs_seen == sorted(epochs_seen), recs
    # Epoch 1 was interrupted mid-epoch (its end-of-epoch record never
    # ran — that IS the eviction); the resume picks up at epoch 2 from
    # the emergency checkpoint's mid-epoch-1 state.
    assert {0, 2, 5} <= set(epochs_seen), epochs_seen
    assert 1 not in epochs_seen, epochs_seen
    losses = [r["loss"] for r in recs]
    assert all(math.isfinite(v) for v in losses), losses
    for prev, cur in zip(recs, recs[1:]):
        assert cur["loss"] <= prev["loss"] * 1.35 + 0.05, (prev, cur)
    assert losses[-1] < losses[0], losses
