"""Parallelism strategies on the virtual 8-device mesh: hierarchical
collectives vs flat equivalents, ring/Ulysses attention vs single-device
attention, TP layers vs dense reference, pipeline vs sequential stages."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

from horovod_tpu import parallel
from horovod_tpu.models.transformer import (
    causal_attention,
    dot_product_attention,
)


def _smap(fn, mesh, in_specs, out_specs):
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_vma=False)


@pytest.fixture(scope="module")
def devs():
    d = jax.devices()
    assert len(d) == 8, "conftest must provide 8 virtual devices"
    return d


# -- hierarchical collectives ------------------------------------------------

def test_hierarchical_allreduce_matches_flat(devs):
    mesh = parallel.hybrid_mesh({"dcn": 2, "ici": 4}, devs)
    x = np.random.RandomState(0).randn(8, 5, 3).astype(np.float32)

    def body(xs):
        return parallel.hierarchical_allreduce(xs[0], "ici", "dcn")[None]

    spec = P(("dcn", "ici"))
    out = _smap(body, mesh, spec, spec)(x)
    expect = x.sum(axis=0)
    for row in np.asarray(out).reshape(8, 5, 3):
        np.testing.assert_allclose(row, expect, rtol=1e-5)


def test_hierarchical_allreduce_average_and_padding(devs):
    mesh = parallel.hybrid_mesh({"dcn": 4, "ici": 2}, devs)
    # 7 elements: not divisible by ici=2, exercises the pad path
    # (reference analogue: FUSION_BUFFER_ATOMIC_UNIT, operations.h:52-54).
    x = np.random.RandomState(1).randn(8, 7).astype(np.float32)

    def body(xs):
        return parallel.hierarchical_allreduce(
            xs[0], "ici", "dcn", average=True)[None]

    spec = P(("dcn", "ici"))
    out = _smap(body, mesh, spec, spec)(x)
    for row in np.asarray(out).reshape(8, 7):
        np.testing.assert_allclose(row, x.mean(axis=0), rtol=1e-5)


def test_hierarchical_allgather_rank_order(devs):
    mesh = parallel.hybrid_mesh({"dcn": 2, "ici": 4}, devs)
    x = np.arange(16, dtype=np.float32).reshape(8, 2)  # rank r: [2r, 2r+1]

    def body(xs):
        return parallel.hierarchical_allgather(xs[0], "ici", "dcn")[None]

    spec = P(("dcn", "ici"))
    out = _smap(body, mesh, spec, spec)(x)
    got = np.asarray(out).reshape(8, 16)
    for row in got:
        np.testing.assert_array_equal(row, np.arange(16))


# -- ring attention ----------------------------------------------------------

@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_exact(devs, causal):
    mesh = parallel.hybrid_mesh({"sp": 8}, devs)
    rng = np.random.RandomState(2)
    b, s, h, d = 2, 32, 2, 4
    q, k, v = (rng.randn(b, s, h, d).astype(np.float32) for _ in range(3))
    ref = (causal_attention if causal else dot_product_attention)(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))

    def body(q, k, v):
        return parallel.ring_attention(q, k, v, "sp", causal=causal)

    spec = P(None, "sp", None, None)
    out = _smap(body, mesh, (spec, spec, spec), spec)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_with_bias(devs):
    mesh = parallel.hybrid_mesh({"sp": 4}, devs[:4])
    rng = np.random.RandomState(3)
    b, s, h, d = 1, 16, 2, 4
    q, k, v = (rng.randn(b, s, h, d).astype(np.float32) for _ in range(3))
    bias = rng.randn(b, h, s, s).astype(np.float32)
    ref = dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v), jnp.asarray(bias))

    def body(q, k, v, bias):
        return parallel.ring_attention(q, k, v, "sp", bias=bias)

    spec = P(None, "sp", None, None)
    bspec = P(None, None, "sp", None)  # bias sharded on the *query* dim
    out = _smap(body, mesh, (spec, spec, spec, bspec), spec)(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


# -- Ulysses -----------------------------------------------------------------

def test_ulysses_attention_exact(devs):
    mesh = parallel.hybrid_mesh({"sp": 8}, devs)
    rng = np.random.RandomState(4)
    b, s, h, d = 2, 32, 8, 4
    q, k, v = (rng.randn(b, s, h, d).astype(np.float32) for _ in range(3))
    ref = dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v))

    def body(q, k, v):
        return parallel.ulysses_attention(q, k, v, "sp")

    spec = P(None, "sp", None, None)
    out = _smap(body, mesh, (spec, spec, spec), spec)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_ulysses_attention_with_bias(devs):
    mesh = parallel.hybrid_mesh({"sp": 4}, devs[:4])
    rng = np.random.RandomState(7)
    b, s, h, d = 1, 16, 4, 4
    q, k, v = (rng.randn(b, s, h, d).astype(np.float32) for _ in range(3))
    bias = rng.randn(b, h, s, s).astype(np.float32)
    ref = dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v), jnp.asarray(bias))

    def body(q, k, v, bias):
        return parallel.ulysses_attention(q, k, v, "sp", bias=bias)

    spec = P(None, "sp", None, None)
    bspec = P(None, None, "sp", None)  # same layout as ring_attention's
    out = _smap(body, mesh, (spec, spec, spec, bspec), spec)(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_ulysses_rejects_indivisible_heads(devs):
    mesh = parallel.hybrid_mesh({"sp": 8}, devs)
    x = np.zeros((1, 8, 4, 2), np.float32)  # 4 heads, 8-way sp

    def body(q):
        return parallel.ulysses_attention(q, q, q, "sp")

    spec = P(None, "sp", None, None)
    with pytest.raises(ValueError, match="divisible"):
        _smap(body, mesh, spec, spec)(x)


# -- tensor parallel ---------------------------------------------------------

def test_parallel_mlp_matches_dense(devs):
    mesh = parallel.hybrid_mesh({"tp": 8}, devs)
    rng = np.random.RandomState(5)
    hid, mlp = 16, 32
    x = rng.randn(4, hid).astype(np.float32)
    w1 = rng.randn(hid, mlp).astype(np.float32)
    b1 = rng.randn(mlp).astype(np.float32)
    w2 = rng.randn(mlp, hid).astype(np.float32)
    b2 = rng.randn(hid).astype(np.float32)
    import flax.linen as nn

    ref = np.asarray(nn.gelu(jnp.asarray(x) @ w1 + b1) @ w2 + b2)

    mlp_mod = parallel.ParallelMLP(hidden_dim=hid, mlp_dim=mlp,
                                   dtype=jnp.float32)

    def body(x, w1, b1, w2, b2):
        params = {"wi": {"kernel": w1, "bias": b1},
                  "wo": {"kernel": w2, "bias": b2}}
        return mlp_mod.apply({"params": params}, x)

    out = _smap(
        body, mesh,
        (P(), P(None, "tp"), P("tp"), P("tp", None), P()),
        P(),
    )(x, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)


def test_column_parallel_rejects_indivisible(devs):
    mesh = parallel.hybrid_mesh({"tp": 8}, devs)
    mod = parallel.ColumnParallelDense(12, dtype=jnp.float32)  # 12 % 8 != 0

    def body(x):
        return mod.init(jax.random.PRNGKey(0), x)["params"]["kernel"]

    with pytest.raises(ValueError, match="divisible"):
        _smap(body, mesh, P(), P(None, "tp"))(np.zeros((2, 4), np.float32))


# -- pipeline ----------------------------------------------------------------

@pytest.mark.parametrize("m", [4, 8])
def test_pipeline_matches_sequential(devs, m):
    p = 4
    mesh = parallel.hybrid_mesh({"pp": p}, devs[:p])
    rng = np.random.RandomState(6)
    # Stage s: x -> tanh(x @ W_s + b_s)
    ws = rng.randn(p, 6, 6).astype(np.float32) * 0.5
    bs = rng.randn(p, 6).astype(np.float32) * 0.1
    x = rng.randn(m, 3, 6).astype(np.float32)  # m microbatches of (3, 6)

    expect = x.copy()
    for s in range(p):
        expect = np.tanh(expect @ ws[s] + bs[s])

    def stage_fn(params, a):
        w, b = params
        return jnp.tanh(a @ w + b)

    def body(ws, bs, x):
        return parallel.pipeline_apply(stage_fn, (ws[0], bs[0]), x, "pp")

    out = _smap(
        body, mesh, (P("pp"), P("pp"), P()), P()
    )(ws, bs, x)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5, atol=1e-5)


# -- hybrid 4D step ----------------------------------------------------------

def test_hybrid_4d_step_trains(devs):
    """One dp×pp×tp×sp step must run and reduce the loss."""
    from horovod_tpu.parallel import hybrid

    l0, l1 = hybrid.dryrun(8, devs)
    assert l1 < l0, (l0, l1)


def test_hybrid_partition_axes():
    from horovod_tpu.parallel.hybrid import partition_axes

    assert partition_axes(8) == {"dp": 1, "pp": 2, "tp": 2, "sp": 2}
    assert partition_axes(16) == {"dp": 2, "pp": 2, "tp": 2, "sp": 2}
    assert partition_axes(1) == {"dp": 1, "pp": 1, "tp": 1, "sp": 1}
    assert partition_axes(6) == {"dp": 3, "pp": 2, "tp": 1, "sp": 1}


def test_mesh_validation(devs):
    with pytest.raises(ValueError, match="devices"):
        parallel.hybrid_mesh({"dp": 3}, devs)


def test_two_tier_mesh_single_host(devs):
    mesh = parallel.two_tier_mesh(devs)
    assert mesh.axis_names == ("dcn", "ici")
    assert mesh.devices.shape == (1, 8)
