"""Parallelism strategies on the virtual 8-device mesh: hierarchical
collectives vs flat equivalents, ring/Ulysses attention vs single-device
attention, TP layers vs dense reference, pipeline vs sequential stages."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu.common.compat import shard_map

from horovod_tpu import parallel
from horovod_tpu.models.transformer import (
    causal_attention,
    dot_product_attention,
)


def _smap(fn, mesh, in_specs, out_specs):
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_vma=False)


@pytest.fixture(scope="module")
def devs():
    d = jax.devices()
    assert len(d) == 8, "conftest must provide 8 virtual devices"
    return d


# -- hierarchical collectives ------------------------------------------------

def test_hierarchical_allreduce_matches_flat(devs):
    mesh = parallel.hybrid_mesh({"dcn": 2, "ici": 4}, devs)
    x = np.random.RandomState(0).randn(8, 5, 3).astype(np.float32)

    def body(xs):
        return parallel.hierarchical_allreduce(xs[0], "ici", "dcn")[None]

    spec = P(("dcn", "ici"))
    out = _smap(body, mesh, spec, spec)(x)
    expect = x.sum(axis=0)
    for row in np.asarray(out).reshape(8, 5, 3):
        np.testing.assert_allclose(row, expect, rtol=1e-5)


def test_hierarchical_allreduce_average_and_padding(devs):
    mesh = parallel.hybrid_mesh({"dcn": 4, "ici": 2}, devs)
    # 7 elements: not divisible by ici=2, exercises the pad path
    # (reference analogue: FUSION_BUFFER_ATOMIC_UNIT, operations.h:52-54).
    x = np.random.RandomState(1).randn(8, 7).astype(np.float32)

    def body(xs):
        return parallel.hierarchical_allreduce(
            xs[0], "ici", "dcn", average=True)[None]

    spec = P(("dcn", "ici"))
    out = _smap(body, mesh, spec, spec)(x)
    for row in np.asarray(out).reshape(8, 7):
        np.testing.assert_allclose(row, x.mean(axis=0), rtol=1e-5)


def test_hierarchical_allgather_rank_order(devs):
    mesh = parallel.hybrid_mesh({"dcn": 2, "ici": 4}, devs)
    x = np.arange(16, dtype=np.float32).reshape(8, 2)  # rank r: [2r, 2r+1]

    def body(xs):
        return parallel.hierarchical_allgather(xs[0], "ici", "dcn")[None]

    spec = P(("dcn", "ici"))
    out = _smap(body, mesh, spec, spec)(x)
    got = np.asarray(out).reshape(8, 16)
    for row in got:
        np.testing.assert_array_equal(row, np.arange(16))


# -- ring attention ----------------------------------------------------------

@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_exact(devs, causal):
    mesh = parallel.hybrid_mesh({"sp": 8}, devs)
    rng = np.random.RandomState(2)
    b, s, h, d = 2, 32, 2, 4
    q, k, v = (rng.randn(b, s, h, d).astype(np.float32) for _ in range(3))
    ref = (causal_attention if causal else dot_product_attention)(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))

    def body(q, k, v):
        return parallel.ring_attention(q, k, v, "sp", causal=causal)

    spec = P(None, "sp", None, None)
    out = _smap(body, mesh, (spec, spec, spec), spec)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_with_bias(devs):
    mesh = parallel.hybrid_mesh({"sp": 4}, devs[:4])
    rng = np.random.RandomState(3)
    b, s, h, d = 1, 16, 2, 4
    q, k, v = (rng.randn(b, s, h, d).astype(np.float32) for _ in range(3))
    bias = rng.randn(b, h, s, s).astype(np.float32)
    ref = dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v), jnp.asarray(bias))

    def body(q, k, v, bias):
        return parallel.ring_attention(q, k, v, "sp", bias=bias)

    spec = P(None, "sp", None, None)
    bspec = P(None, None, "sp", None)  # bias sharded on the *query* dim
    out = _smap(body, mesh, (spec, spec, spec, bspec), spec)(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


# -- Ulysses -----------------------------------------------------------------

def test_ulysses_attention_exact(devs):
    mesh = parallel.hybrid_mesh({"sp": 8}, devs)
    rng = np.random.RandomState(4)
    b, s, h, d = 2, 32, 8, 4
    q, k, v = (rng.randn(b, s, h, d).astype(np.float32) for _ in range(3))
    ref = dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v))

    def body(q, k, v):
        return parallel.ulysses_attention(q, k, v, "sp")

    spec = P(None, "sp", None, None)
    out = _smap(body, mesh, (spec, spec, spec), spec)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_ulysses_attention_with_bias(devs):
    mesh = parallel.hybrid_mesh({"sp": 4}, devs[:4])
    rng = np.random.RandomState(7)
    b, s, h, d = 1, 16, 4, 4
    q, k, v = (rng.randn(b, s, h, d).astype(np.float32) for _ in range(3))
    bias = rng.randn(b, h, s, s).astype(np.float32)
    ref = dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v), jnp.asarray(bias))

    def body(q, k, v, bias):
        return parallel.ulysses_attention(q, k, v, "sp", bias=bias)

    spec = P(None, "sp", None, None)
    bspec = P(None, None, "sp", None)  # same layout as ring_attention's
    out = _smap(body, mesh, (spec, spec, spec, bspec), spec)(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_ulysses_rejects_indivisible_heads(devs):
    mesh = parallel.hybrid_mesh({"sp": 8}, devs)
    x = np.zeros((1, 8, 4, 2), np.float32)  # 4 heads, 8-way sp

    def body(q):
        return parallel.ulysses_attention(q, q, q, "sp")

    spec = P(None, "sp", None, None)
    with pytest.raises(ValueError, match="divisible"):
        _smap(body, mesh, spec, spec)(x)


# -- tensor parallel ---------------------------------------------------------

def test_parallel_mlp_matches_dense(devs):
    mesh = parallel.hybrid_mesh({"tp": 8}, devs)
    rng = np.random.RandomState(5)
    hid, mlp = 16, 32
    x = rng.randn(4, hid).astype(np.float32)
    w1 = rng.randn(hid, mlp).astype(np.float32)
    b1 = rng.randn(mlp).astype(np.float32)
    w2 = rng.randn(mlp, hid).astype(np.float32)
    b2 = rng.randn(hid).astype(np.float32)
    import flax.linen as nn

    ref = np.asarray(nn.gelu(jnp.asarray(x) @ w1 + b1) @ w2 + b2)

    mlp_mod = parallel.ParallelMLP(hidden_dim=hid, mlp_dim=mlp,
                                   dtype=jnp.float32)

    def body(x, w1, b1, w2, b2):
        params = {"wi": {"kernel": w1, "bias": b1},
                  "wo": {"kernel": w2, "bias": b2}}
        return mlp_mod.apply({"params": params}, x)

    out = _smap(
        body, mesh,
        (P(), P(None, "tp"), P("tp"), P("tp", None), P()),
        P(),
    )(x, w1, b1, w2, b2)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)


def test_column_parallel_rejects_indivisible(devs):
    mesh = parallel.hybrid_mesh({"tp": 8}, devs)
    mod = parallel.ColumnParallelDense(12, dtype=jnp.float32)  # 12 % 8 != 0

    def body(x):
        return mod.init(jax.random.PRNGKey(0), x)["params"]["kernel"]

    with pytest.raises(ValueError, match="divisible"):
        _smap(body, mesh, P(), P(None, "tp"))(np.zeros((2, 4), np.float32))


# -- pipeline ----------------------------------------------------------------

@pytest.mark.parametrize("m", [4, 8])
def test_pipeline_matches_sequential(devs, m):
    p = 4
    mesh = parallel.hybrid_mesh({"pp": p}, devs[:p])
    rng = np.random.RandomState(6)
    # Stage s: x -> tanh(x @ W_s + b_s)
    ws = rng.randn(p, 6, 6).astype(np.float32) * 0.5
    bs = rng.randn(p, 6).astype(np.float32) * 0.1
    x = rng.randn(m, 3, 6).astype(np.float32)  # m microbatches of (3, 6)

    expect = x.copy()
    for s in range(p):
        expect = np.tanh(expect @ ws[s] + bs[s])

    def stage_fn(params, a):
        w, b = params
        return jnp.tanh(a @ w + b)

    def body(ws, bs, x):
        return parallel.pipeline_apply(stage_fn, (ws[0], bs[0]), x, "pp")

    out = _smap(
        body, mesh, (P("pp"), P("pp"), P()), P()
    )(ws, bs, x)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5, atol=1e-5)


# -- MoE / expert parallelism ------------------------------------------------

def _moe_reference(x, router_w, wi, wo, capacity):
    """Per-token reference: gate * FFN_e(x) when within capacity, else 0."""
    import scipy.special

    logits = x @ router_w
    probs = scipy.special.softmax(logits, axis=-1)
    e_idx = np.argmax(probs, axis=-1)
    gate = np.max(probs, axis=-1)
    counts = {}
    out = np.zeros_like(x)
    for t in range(len(x)):
        e = int(e_idx[t])
        k = counts.get(e, 0)
        counts[e] = k + 1
        if k >= capacity:
            continue
        h = np.asarray(jax.nn.gelu(jnp.asarray(x[t] @ wi[e])))
        out[t] = gate[t] * (h @ wo[e])
    return out


def test_moe_layer_matches_reference(devs):
    ep = 4
    mesh = parallel.hybrid_mesh({"ep": ep}, devs[:ep])
    rng = np.random.RandomState(8)
    t_local, hidden, ff, e_local = 16, 8, 16, 2
    n_experts = ep * e_local
    x = rng.randn(ep * t_local, hidden).astype(np.float32)
    router = rng.randn(hidden, n_experts).astype(np.float32)
    wi = rng.randn(n_experts, hidden, ff).astype(np.float32) * 0.3
    wo = rng.randn(n_experts, ff, hidden).astype(np.float32) * 0.3
    cf = 4.0  # capacity ample: no drops
    capacity = max(1, int(t_local * cf / n_experts))

    def body(x, router, wi, wo):
        y, aux = parallel.moe_layer(x, router, wi, wo, "ep",
                                    capacity_factor=cf)
        return y, aux

    y, aux = _smap(
        body, mesh,
        (P("ep"), P(), P("ep"), P("ep")), (P("ep"), P()),
    )(x, router, wi, wo)
    # Reference per chip block (routing/capacity is per-chip).
    expect = np.concatenate([
        _moe_reference(x[c * t_local:(c + 1) * t_local], router, wi, wo,
                       capacity)
        for c in range(ep)
    ])
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-4, atol=1e-5)
    assert float(aux) > 0.0


def test_moe_capacity_drops_tokens(devs):
    ep = 2
    mesh = parallel.hybrid_mesh({"ep": ep}, devs[:ep])
    rng = np.random.RandomState(9)
    x = rng.randn(2 * 32, 8).astype(np.float32)
    # Router forcing every token to expert 0 -> most exceed capacity.
    router = np.zeros((8, 2), np.float32)
    router[:, 0] = 1.0
    x = np.abs(x)  # positive activations -> logits favor expert 0
    wi = rng.randn(2, 8, 8).astype(np.float32)
    wo = rng.randn(2, 8, 8).astype(np.float32)

    def body(x, router, wi, wo):
        y, aux = parallel.moe_layer(x, router, wi, wo, "ep",
                                    capacity_factor=0.25)
        return y, aux

    y, _ = _smap(body, mesh, (P("ep"), P(), P("ep"), P("ep")),
                 (P("ep"), P()))(x, router, wi, wo)
    zero_rows = np.sum(~np.any(np.asarray(y), axis=1))
    assert zero_rows > 0  # overflow tokens passed through as zeros


# -- hybrid 4D step ----------------------------------------------------------

def test_hybrid_4d_step_trains(devs):
    """One dp×pp×tp×sp step must run and reduce the loss."""
    from horovod_tpu.parallel import hybrid

    l0, l1 = hybrid.dryrun(8, devs)
    assert l1 < l0, (l0, l1)


def test_hybrid_stage_params_replicated_across_ep(devs):
    """Router/attention/MLP weights must be IDENTICAL across ep chips;
    only expert weights (wi/wo) may differ — divergent shared params would
    silently desynchronize the ep replicas."""
    import jax
    from horovod_tpu.parallel import hybrid

    mesh = parallel.hybrid_mesh(
        {"dp": 1, "pp": 1, "tp": 1, "sp": 1, "ep": 2}, devs[:2])
    cfg = hybrid.HybridConfig()

    def body(key):
        import jax.numpy as jnp
        from jax import lax

        stage = hybrid.HybridStage(cfg)
        stage_key = jax.random.fold_in(
            jax.random.fold_in(key[0], lax.axis_index("pp")),
            lax.axis_index("tp"))
        dummy = jnp.zeros((2, cfg.seq_len, cfg.hidden_dim), cfg.dtype)
        p = stage.init(stage_key, dummy)["params"]
        return (p["moe_router_0"][None], p["moe_wi_0"][None],
                p["q_0"]["kernel"][None])

    router, wi, qk = _smap(
        body, mesh, P(), (P("ep"), P("ep"), P("ep"))
    )(jax.random.PRNGKey(0)[None])
    router, wi, qk = (np.asarray(t) for t in (router, wi, qk))
    np.testing.assert_array_equal(router[0], router[1])
    np.testing.assert_array_equal(qk[0], qk[1])
    assert not np.allclose(wi[0], wi[1]), "experts must be sharded"


def test_hybrid_without_ep_axis(devs):
    """use_moe=False must work on a mesh with NO ep axis (the 4-axis mesh
    documented in docs/parallelism.md)."""
    import jax
    from horovod_tpu.parallel import hybrid

    mesh = parallel.hybrid_mesh(
        {"dp": 1, "pp": 2, "tp": 2, "sp": 2}, devs)
    cfg = hybrid.HybridConfig(use_moe=False)
    step, _ = hybrid.build_train_step(mesh, cfg)
    tokens = np.random.RandomState(0).randint(
        0, cfg.vocab_size, size=(2 * cfg.microbatches, cfg.seq_len)
    ).astype(np.int32)
    l0, l1 = step(tokens, jax.random.PRNGKey(0))
    assert float(l1) < float(l0)


def test_hybrid_partition_axes():
    from horovod_tpu.parallel.hybrid import partition_axes

    assert partition_axes(8) == {"dp": 1, "pp": 2, "tp": 2, "sp": 2,
                                 "ep": 1}
    assert partition_axes(16) == {"dp": 1, "pp": 2, "tp": 2, "sp": 2,
                                  "ep": 2}
    assert partition_axes(1) == {"dp": 1, "pp": 1, "tp": 1, "sp": 1,
                                 "ep": 1}
    assert partition_axes(6) == {"dp": 3, "pp": 2, "tp": 1, "sp": 1,
                                 "ep": 1}


def test_mesh_validation(devs):
    with pytest.raises(ValueError, match="devices"):
        parallel.hybrid_mesh({"dp": 3}, devs)


def test_two_tier_mesh_single_host(devs):
    mesh = parallel.two_tier_mesh(devs)
    assert mesh.axis_names == ("dcn", "ici")
    assert mesh.devices.shape == (1, 8)
