"""hvdcheck (horovod_tpu/analysis): the static-analysis suite itself.

Tiers in this file:

- live-tree: every checker runs against the REAL repository and must
  come back clean — this is what wires the analyzer into tier-1 CI, so
  an ABI/parity/invariant drift fails the commit it lands in;
- mutation corpus: copies of the real hvdcore.cc / ctypes binding with
  one seeded skew each (swapped C fields, widened ctypes field, skewed
  argtypes, renamed C++ counter field, renamed span) — the ABI/parity
  checkers must catch every one, proving they diff the real files and
  not a cached model of them;
- rule fixtures: hand-written violation snippets for each invariant
  rule (per-tensor TF bridge, engine destroy/abandon-join, donate-then-
  mutate, missing eager drain, lock inversion, non-stdlib entrypoint
  import);
- CLI: the exit-code contract (0 clean / 2 findings) on a mini tree;
- slow (HVD_SLOW_TESTS=1): the native-engine TSan smoke —
  HVD_SANITIZE=thread build + a multi-threaded engine workout under
  LD_PRELOAD'd libtsan with the shipped suppression file.
"""

import ast
import json
import os
import shutil
import subprocess
import sys

import pytest

from horovod_tpu import analysis
from horovod_tpu.analysis import abi, invariants, parity, report

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_slow_on = os.environ.get("HVD_SLOW_TESTS", "").lower() in (
    "1", "true", "yes", "on")


# ---------------------------------------------------------------------------
# live tree: the analyzer IS tier-1 CI
# ---------------------------------------------------------------------------


def test_live_tree_is_clean():
    findings = analysis.run_all(REPO)
    assert findings == [], "\n".join(f.format() for f in findings)


def test_every_rule_is_cataloged_and_documented():
    doc = open(os.path.join(REPO, "docs", "static-analysis.md")).read()
    for rule in report.RULE_CATALOG:
        assert rule in doc, f"rule {rule!r} missing from the catalog doc"


# ---------------------------------------------------------------------------
# mutation corpus: the ABI/parity checkers diff the REAL files
# ---------------------------------------------------------------------------

_CORE_FILES = ("engine.py", "native_engine.py", "bufferpool.py",
               "timeline.py", "telemetry.py", "doctor.py")


def _mini_root(tmp_path):
    """A copy of exactly the files the checkers read, so mutations can
    be seeded without touching the live tree."""
    core = tmp_path / "horovod_tpu" / "core"
    native = core / "native"
    utils = tmp_path / "horovod_tpu" / "utils"
    native.mkdir(parents=True)
    utils.mkdir()
    for f in _CORE_FILES:
        shutil.copy(os.path.join(REPO, "horovod_tpu", "core", f), core)
    for f in ("hvdcore.cc", "__init__.py"):
        shutil.copy(os.path.join(REPO, "horovod_tpu", "core", "native", f),
                    native)
    shutil.copy(os.path.join(REPO, "horovod_tpu", "utils", "stats.py"),
                utils)
    shutil.copy(os.path.join(REPO, "bench.py"), tmp_path)
    shutil.copy(os.path.join(REPO, "horovod_tpu", "run.py"),
                tmp_path / "horovod_tpu")
    return str(tmp_path)


def _edit(root, rel, old, new):
    path = os.path.join(root, rel)
    src = open(path).read()
    assert old in src, f"mutation anchor not found in {rel}: {old!r}"
    open(path, "w").write(src.replace(old, new))


_CC = os.path.join("horovod_tpu", "core", "native", "hvdcore.cc")
_BINDING = os.path.join("horovod_tpu", "core", "native", "__init__.py")
_NATIVE_PY = os.path.join("horovod_tpu", "core", "native_engine.py")


def test_mini_root_baseline_is_clean(tmp_path):
    root = _mini_root(tmp_path)
    findings = analysis.run_all(root)
    assert findings == [], "\n".join(f.format() for f in findings)


def test_abi_catches_swapped_c_struct_fields(tmp_path):
    root = _mini_root(tmp_path)
    _edit(root, _CC, "int itemsize;\n  int average;",
          "int average;\n  int itemsize;")
    rules = {f.rule for f in abi.check(root)}
    assert rules == {"abi-struct"}


def test_abi_catches_skewed_ctypes_field(tmp_path):
    """The issue's canonical seed: a ctypes mirror field narrowed behind
    the C struct's back."""
    root = _mini_root(tmp_path)
    _edit(root, _BINDING, '("wire_bytes", ctypes.c_longlong),',
          '("wire_bytes", ctypes.c_int),')
    findings = abi.check(root)
    assert any(f.rule == "abi-struct" and "wire_bytes" in f.message
               for f in findings), findings


def test_abi_catches_new_c_field_missing_from_mirror(tmp_path):
    root = _mini_root(tmp_path)
    _edit(root, _CC, "long long admission_bytes_low;\n};",
          "long long admission_bytes_low;\n  long long new_counter;\n};")
    findings = abi.check(root)
    assert any(f.rule == "abi-struct" and "new_counter" in f.message
               for f in findings), findings


def test_abi_catches_enqueue_n_argtype_skew(tmp_path):
    """The batched-submit entry point is machine-diffed like every other
    hvd_* symbol: narrowing the request-array pointer in the ctypes
    mirror must be named."""
    root = _mini_root(tmp_path)
    _edit(root, _BINDING,
          "lib.hvd_engine_enqueue_n.argtypes = [\n"
          "        ctypes.c_void_p, ctypes.POINTER(HvdRequest), "
          "ctypes.c_int,\n"
          "        ctypes.POINTER(ctypes.c_longlong), ctypes.c_char_p]",
          "lib.hvd_engine_enqueue_n.argtypes = [\n"
          "        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,\n"
          "        ctypes.POINTER(ctypes.c_longlong), ctypes.c_char_p]")
    findings = abi.check(root)
    assert any(f.rule == "abi-signature"
               and "hvd_engine_enqueue_n" in f.message
               for f in findings), findings


def test_abi_catches_argtype_skew(tmp_path):
    root = _mini_root(tmp_path)
    _edit(root, _BINDING,
          "lib.hvd_engine_poll.argtypes = [ctypes.c_void_p, "
          "ctypes.c_longlong]",
          "lib.hvd_engine_poll.argtypes = [ctypes.c_void_p, ctypes.c_int]")
    findings = abi.check(root)
    assert any(f.rule == "abi-signature" and "hvd_engine_poll" in f.message
               for f in findings), findings


def test_abi_catches_callback_typedef_skew(tmp_path):
    root = _mini_root(tmp_path)
    _edit(root, _CC,
          "typedef int (*hvd_negotiate_fn)(void* ctx, const char* "
          "table_json,\n                                char** "
          "decision_out);",
          "typedef int (*hvd_negotiate_fn)(void* ctx, const char* "
          "table_json,\n                                long long epoch,"
          "\n                                char** decision_out);")
    findings = abi.check(root)
    assert any(f.rule == "abi-callback" for f in findings), findings


def test_parity_catches_renamed_cxx_counter_field(tmp_path):
    """The issue's canonical seed: a C++ stats counter renamed without
    the stats sync following."""
    root = _mini_root(tmp_path)
    _edit(root, _CC, "long long fused_batches;", "long long fused_groups;")
    rules = {f.rule for f in parity.check(root)}
    assert "parity-stats-fields" in rules
    # ...and the ABI checker flags the layout skew independently.
    assert any(f.rule == "abi-struct" for f in abi.check(root))


def test_parity_catches_renamed_ring_counter_field(tmp_path):
    """The batched-submit stats tail (ring_full/ring_spins/
    pool_bound_hits) is covered by the same stats-field diff as the
    legacy counters."""
    root = _mini_root(tmp_path)
    _edit(root, _CC, "long long ring_full;", "long long ring_stalls;")
    rules = {f.rule for f in parity.check(root)}
    assert "parity-stats-fields" in rules
    assert any(f.rule == "abi-struct" for f in abi.check(root))


def test_parity_catches_renamed_cxx_span(tmp_path):
    root = _mini_root(tmp_path)
    _edit(root, _CC, '"MEMCPY_IN_FUSION_BUFFER"', '"MEMCPY_INTO_FUSION"')
    findings = parity.check(root)
    assert any(f.rule == "parity-spans" and "MEMCPY_INTO_FUSION"
               in f.message for f in findings), findings


def test_parity_catches_python_only_counter(tmp_path):
    root = _mini_root(tmp_path)
    _edit(root, os.path.join("horovod_tpu", "core", "engine.py"),
          'tele.REGISTRY.counter("engine.cycles").inc()',
          'tele.REGISTRY.counter("engine.cycles_total").inc()')
    findings = parity.check(root)
    assert any(f.rule == "parity-counters" for f in findings), findings


def test_parity_catches_dtype_table_skew(tmp_path):
    root = _mini_root(tmp_path)
    _edit(root, _CC, '"float32",  "float64", "float16"',
          '"float32",  "float16", "float64"')
    findings = parity.check(root)
    assert any(f.rule == "parity-dtypes" for f in findings), findings


def test_parity_catches_unhandled_decision_kind(tmp_path):
    root = _mini_root(tmp_path)
    _edit(root, _NATIVE_PY, 'lines.append(f"w {decision.idle_backoff_s}")',
          'lines.append(f"z {decision.idle_backoff_s}")')
    findings = parity.check(root)
    assert any(f.rule == "parity-grammar" and "'z'" in f.message
               for f in findings), findings


def test_parity_catches_wire_code_skew(tmp_path):
    root = _mini_root(tmp_path)
    _edit(root, _CC, 'case 2: return "fp8";', 'case 3: return "fp8";')
    findings = parity.check(root)
    assert any(f.rule == "parity-wire-codes" for f in findings), findings


def test_abi_catches_skewed_wire_dcn_field(tmp_path):
    """The per-tier DCN wire policy rides the C ABI (hvd_request.wire_dcn);
    widening the ctypes mirror behind the C struct's back must be named."""
    root = _mini_root(tmp_path)
    _edit(root, _BINDING, '("wire_dcn", ctypes.c_int),',
          '("wire_dcn", ctypes.c_longlong),')
    findings = abi.check(root)
    assert any(f.rule == "abi-struct" and "wire_dcn" in f.message
               for f in findings), findings


def test_parity_catches_renamed_tier_counter_field(tmp_path):
    """The per-tier wire byte counters (wire_bytes_dcn/_ici) join the
    machine-diffed stats vocabulary: renaming the C++ side without the
    stats sync following is named by both checkers."""
    root = _mini_root(tmp_path)
    _edit(root, _CC, "long long wire_bytes_dcn;",
          "long long wire_bytes_slow;")
    rules = {f.rule for f in parity.check(root)}
    assert "parity-stats-fields" in rules
    assert any(f.rule == "abi-struct" for f in abi.check(root))


def test_parity_catches_renamed_tier_span_arg(tmp_path):
    """Timeline span args carry the per-tier policy ("wire_dcn"); the
    C++ emitter drifting from the python vocabulary is a span-args skew."""
    root = _mini_root(tmp_path)
    _edit(root, _CC, 'out += ", \\"wire_dcn\\": \\"";',
          'out += ", \\"dcn_wire\\": \\"";')
    findings = parity.check(root)
    assert any(f.rule == "parity-span-args" for f in findings), findings


def test_parity_catches_skewed_latency_bucket_edge(tmp_path):
    """The issue's canonical seed: one C++ bucket edge nudged — merged
    world histograms would silently corrupt every fleet quantile."""
    root = _mini_root(tmp_path)
    _edit(root, _CC, "1e-4, 3e-4, 1e-3", "2e-4, 3e-4, 1e-3")
    findings = parity.check(root)
    assert any(f.rule == "parity-latency" and "kLatencyBucketsS"
               in f.message for f in findings), findings


def test_parity_catches_renamed_latency_struct_field(tmp_path):
    """A renamed hvd_engine_latency field skews both the _LATENCY_HISTS
    fold target (parity) and the ctypes mirror layout (abi)."""
    root = _mini_root(tmp_path)
    _edit(root, _CC, "long long phase_exec[13];",
          "long long phase_execute[13];")
    findings = parity.check(root)
    assert any(f.rule == "parity-latency" and "phase_exec" in f.message
               for f in findings), findings
    assert any(f.rule == "abi-struct" for f in abi.check(root))


def test_parity_doctor_catches_skewed_cxx_inspect_key(tmp_path):
    """The issue's canonical seed: one C++ inspect-record JSON key
    renamed — the doctor's cross-rank/cross-engine record diff would
    silently lose that field's attribution."""
    root = _mini_root(tmp_path)
    _edit(root, _CC, '\\"phase_age_us\\":', '\\"phaseage_us\\":')
    findings = parity.check(root)
    assert any(f.rule == "parity-doctor" and "phaseage_us" in f.message
               for f in findings), findings


def test_parity_doctor_catches_renamed_verdict_kind(tmp_path):
    """A verdict kind renamed in the classifier without the stats-CLI
    consumer table following — every console would render it as
    unknown-kind."""
    root = _mini_root(tmp_path)
    _edit(root, os.path.join("horovod_tpu", "core", "doctor.py"),
          '"missing_submitter"', '"missing_sub"')
    findings = parity.check(root)
    assert any(f.rule == "parity-doctor" and "missing_sub" in f.message
               for f in findings), findings


def test_parity_doctor_catches_python_record_skew(tmp_path):
    """The python twin's record builder drifting from the declared
    contract is caught from the engine.py side alone."""
    root = _mini_root(tmp_path)
    _edit(root, os.path.join("horovod_tpu", "core", "engine.py"),
          "phase_age_us=int((now - e.phase_since) * 1e6),",
          "phase_age=int((now - e.phase_since) * 1e6),")
    findings = parity.check(root)
    assert any(f.rule == "parity-doctor" and "ENGINE_INSPECT_KEYS"
               in f.message for f in findings), findings


def test_abi_catches_skewed_priority_field(tmp_path):
    """The serving-plane priority class rides the C ABI
    (hvd_request.priority); widening the ctypes mirror behind the C
    struct's back must be named."""
    root = _mini_root(tmp_path)
    _edit(root, _BINDING, '("priority", ctypes.c_int),',
          '("priority", ctypes.c_longlong),')
    findings = abi.check(root)
    assert any(f.rule == "abi-struct" and "priority" in f.message
               for f in findings), findings


def test_parity_catches_renamed_admission_counter_field(tmp_path):
    """The admission counters (engine.admission.rejected/shed) join the
    machine-diffed stats vocabulary: renaming the C++ field without the
    stats sync following is named by both checkers."""
    root = _mini_root(tmp_path)
    _edit(root, _CC, "long long admission_rejected;",
          "long long admission_refused;")
    rules = {f.rule for f in parity.check(root)}
    assert "parity-stats-fields" in rules
    assert any(f.rule == "abi-struct" for f in abi.check(root))


def test_parity_catches_renamed_admission_span_arg(tmp_path):
    """Timeline span args carry the serving-plane class ("priority");
    the C++ emitter drifting from the python vocabulary is a span-args
    skew."""
    root = _mini_root(tmp_path)
    _edit(root, _CC, 'out += ", \\"priority\\": \\"";',
          'out += ", \\"prio_class\\": \\"";')
    findings = parity.check(root)
    assert any(f.rule == "parity-span-args" for f in findings), findings


def test_parity_doctor_catches_renamed_overload_verdict(tmp_path):
    """The serving-plane 'overload' verdict renamed in the classifier
    without the stats-CLI consumer table following — same contract as
    the other doctor kinds."""
    root = _mini_root(tmp_path)
    _edit(root, os.path.join("horovod_tpu", "core", "doctor.py"),
          '"overload"', '"overloaded"')
    findings = parity.check(root)
    assert any(f.rule == "parity-doctor" and "overloaded" in f.message
               for f in findings), findings


def test_parity_catches_renamed_latency_instrument(tmp_path):
    """A latency instrument renamed on the native fold side only — the
    vocabularies the two engines feed must stay identical."""
    root = _mini_root(tmp_path)
    _edit(root, _NATIVE_PY, '("engine.latency.allreduce", "allreduce"),',
          '("engine.latency.allreduce_s", "allreduce"),')
    findings = parity.check(root)
    assert any(f.rule == "parity-counters"
               and "engine.latency.allreduce" in f.message
               for f in findings), findings


# ---------------------------------------------------------------------------
# invariant rule fixtures: each rule catches its seeded violation
# ---------------------------------------------------------------------------


def _findings_for(snippet: str, rule_fn, rel="fixture.py"):
    tree = ast.parse(snippet)
    return rule_fn(tree, rel)


def test_rule_tf_bridge_catches_per_tensor_blocking_loop():
    bad = '''
import tensorflow as tf

def broken_group(tensors, names):
    def fn(*ts):
        e = get_engine()
        outs = []
        for name, t in zip(names, ts):
            h = e.allreduce_async(name, t.numpy(), True)
            outs.append(e.synchronize(h))  # blocking per tensor: wedges
        return outs
    return tf.py_function(fn, tensors, Tout=[t.dtype for t in tensors])
'''
    findings = _findings_for(bad, invariants.check_tf_bridge)
    assert len(findings) == 1 and findings[0].rule == "tf-bridge-group"


def test_rule_tf_bridge_allows_submit_all_then_wait():
    good = '''
import tensorflow as tf

def grouped(tensors, names):
    def fn(*ts):
        e = get_engine()
        handles = [e.allreduce_async(n, t.numpy(), True)
                   for n, t in zip(names, ts)]
        outs = []
        for h in handles:
            outs.append(e.synchronize(h))
        return outs
    return tf.py_function(fn, tensors, Tout=[t.dtype for t in tensors])
'''
    assert _findings_for(good, invariants.check_tf_bridge) == []


def test_rule_engine_lifecycle_catches_destroy_and_abandon_join():
    bad = '''
def shutdown(self):
    self._lib.hvd_engine_join(self._ptr)
    self._lib.hvd_engine_destroy(self._ptr)  # UB: waiters in WaitMeta

def abandon(self):
    self._lib.hvd_engine_join(self._ptr)  # never returns: loop is wedged
    self._stall_thread.join()
'''
    findings = _findings_for(bad, invariants.check_engine_lifecycle)
    assert {f.rule for f in findings} == {"engine-lifecycle"}
    msgs = " ".join(f.message for f in findings)
    assert "hvd_engine_destroy" in msgs
    assert "hvd_engine_join" in msgs
    assert "_stall_thread" in msgs


def test_rule_donate_mutate_catches_write_after_handoff():
    bad = '''
def step(e, grad):
    h = e.allreduce_async("grad", grad, True, donate=True)
    grad[0] = 0.0  # mutates the engine's in-place reference
    return e.synchronize(h)
'''
    findings = _findings_for(bad, invariants.check_donate_mutate)
    assert len(findings) == 1 and findings[0].rule == "donate-mutate"


def test_rule_donate_mutate_allows_mutation_after_synchronize():
    good = '''
def step(e, grad):
    h = e.allreduce_async("grad", grad, True, donate=True)
    out = e.synchronize(h)
    grad[0] = 0.0  # handle retired: ownership is back
    return out
'''
    assert _findings_for(good, invariants.check_donate_mutate) == []


def test_rule_eager_drain_catches_device_first_broadcast():
    bad = '''
class Trainer:
    def broadcast_state(self, root_rank=0):
        # sharded device arrays handed straight to the eager broadcast
        self.params = broadcast_pytree(self.params, root_rank)
        self.opt_state = broadcast_pytree(self.opt_state, root_rank)
'''
    findings = _findings_for(bad, invariants.check_eager_drain)
    assert {f.rule for f in findings} == {"eager-drain"}
    assert len(findings) == 2  # no host pull AND no drain


def test_rule_eager_drain_allows_host_first_pattern():
    good = '''
class Trainer:
    def broadcast_state(self, root_rank=0):
        host = jax.device_get((self.params, self.opt_state))
        params, opt_state = host
        self.params = broadcast_pytree(params, root_rank)
        self.opt_state = broadcast_pytree(opt_state, root_rank)
        jax.block_until_ready((self.params, self.opt_state))
'''
    assert _findings_for(good, invariants.check_eager_drain) == []


def test_rule_lock_order_catches_inversion():
    bad = '''
class BufferPool:
    def checkout(self, count):
        with self._lock:
            self.engine._complete(None, None, None)  # pool -> engine
            return None

class Engine:
    def _complete(self, e, result, err):
        with self._lock:
            self._handles.pop(0, None)

    def _enqueue(self, entry):
        with self.pool._lock:       # nested inversion: pool held...
            with self._lock:        # ...while taking the engine lock
                pass
'''
    findings = invariants.check_lock_order({"engine.py": ast.parse(bad)})
    assert findings, "lock inversion not caught"
    assert all(f.rule == "lock-order" for f in findings)
    msgs = " ".join(f.message for f in findings)
    assert "checkout" in msgs and "_enqueue" in msgs


def test_rule_lock_order_allows_documented_hierarchy():
    good = '''
class Engine:
    def _enqueue(self, entry):
        with self._lock:
            self._pending[entry.name] = entry

class BufferPool:
    def checkout_tracked(self, count):
        with self._lock:
            self._c_hits.inc()  # telemetry leaf under pool lock: rank 2>3
            return None
'''
    assert invariants.check_lock_order({"engine.py": ast.parse(good)}) == []


def test_rule_entrypoint_imports_catches_framework_import(tmp_path):
    root = _mini_root(tmp_path)
    _edit(root, "bench.py", "import argparse", "import argparse\nimport jax")
    findings = invariants.check_entrypoint_imports(root)
    assert any(f.rule == "entrypoint-imports" and "'jax'" in f.message
               for f in findings), findings


def test_rule_entrypoint_imports_clean_on_live_entrypoints():
    assert invariants.check_entrypoint_imports(REPO) == []


def test_rule_fault_site_registry_clean_on_live_tree():
    assert invariants.check_fault_sites(REPO) == []


def _fault_root(tmp_path):
    """A mini root with the real faultline.py + one consumer + one
    chaos-spec reference, for seeding registry skews."""
    core = tmp_path / "horovod_tpu" / "core"
    core.mkdir(parents=True)
    shutil.copy(os.path.join(REPO, "horovod_tpu", "core", "faultline.py"),
                core)
    (core / "consumer.py").write_text(
        "from horovod_tpu.core import faultline as flt\n\n\n"
        "def submit(name):\n"
        "    injected = flt.engine_submit(name)\n"
        "    flt.engine_admit_burst()\n"
        "    flt.kv_get(name)\n"
        "    flt.kv_set(name, 'v')\n"
        "    flt.kv_try_get(name)\n"
        "    flt.heartbeat()\n"
        "    flt.engine_exec('allreduce')\n"
        "    flt.pool_exhausted()\n"
        "    flt.ckpt_write()\n"
        "    flt.preempt_signal()\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_chaos.py").write_text(
        "SPEC = 'hb.beat:skip:*@8'\n")
    return str(tmp_path)


def test_rule_fault_site_registry_catches_renamed_site(tmp_path):
    """The satellite's canonical seed: a site renamed in the registry
    while a chaos spec still references the old name — the spec would
    silently inject nothing."""
    root = _fault_root(tmp_path)
    assert invariants.check_fault_sites(root) == []
    _edit(root, os.path.join("horovod_tpu", "core", "faultline.py"),
          '"hb.beat"', '"hb.pulse"')
    findings = invariants.check_fault_sites(root)
    assert any("hb.beat" in f.message and f.rule == "fault-site-registry"
               for f in findings), findings


def test_rule_fault_site_registry_catches_unknown_mode(tmp_path):
    root = _fault_root(tmp_path)
    with open(os.path.join(root, "tests", "test_chaos.py"), "a") as fh:
        # (Assembled so the LIVE tree's scan of this very test file
        # does not see a bad spec literal.)
        fh.write("BAD = '" + "engine.exec" + ":explode:1'\n")
    findings = invariants.check_fault_sites(root)
    assert any("'explode'" in f.message for f in findings), findings


def test_rule_fault_site_registry_catches_unthreaded_site(tmp_path):
    """A site whose guard helper is never called from source is declared
    but inert — chaos specs naming it test nothing."""
    root = _fault_root(tmp_path)
    _edit(root, os.path.join("horovod_tpu", "core", "consumer.py"),
          "    flt.ckpt_write()\n", "")
    findings = invariants.check_fault_sites(root)
    assert any("ckpt.write" in f.message and "not threaded" in f.message
               for f in findings), findings


def test_rule_fault_site_registry_exempts_negative_fixtures(tmp_path):
    """Deliberately-invalid specs inside FaultSpecError rejection tests
    are negative fixtures, not site references."""
    root = _fault_root(tmp_path)
    with open(os.path.join(root, "tests", "test_chaos.py"), "a") as fh:
        fh.write(
            "import pytest\n"
            "from horovod_tpu.core import faultline as flt\n\n\n"
            "def test_bad_spec_rejected():\n"
            "    with pytest.raises(flt.FaultSpecError):\n"
            "        flt.configure('no.such" + ":delay:1')\n")
    assert invariants.check_fault_sites(root) == []


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------


def test_cli_exit_codes_and_json(tmp_path):
    from horovod_tpu.analysis.__main__ import main

    # Clean mini tree -> 0.
    root = _mini_root(tmp_path)
    assert main(["--root", root, "--json"]) == 0
    # Seed one violation -> 2.
    _edit(root, _CC, "long long fused_batches;", "long long fused_groups;")
    assert main(["--root", root]) == 2
    assert main(["--list-rules"]) == 0


def test_cli_subprocess_on_live_tree():
    """The `python -m horovod_tpu.analysis` spelling of the tier-1 run."""
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.analysis", "--json"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert proc.returncode == 0, (proc.stdout, proc.stderr[-2000:])
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["count"] == 0


# ---------------------------------------------------------------------------
# sanitizer wiring
# ---------------------------------------------------------------------------


def test_sanitize_mode_validation(monkeypatch):
    from horovod_tpu.core import native

    monkeypatch.delenv("HVD_SANITIZE", raising=False)
    assert native.sanitize_mode() == ""
    monkeypatch.setenv("HVD_SANITIZE", "off")
    assert native.sanitize_mode() == ""
    monkeypatch.setenv("HVD_SANITIZE", "thread")
    assert native.sanitize_mode() == "thread"
    monkeypatch.setenv("HVD_SANITIZE", "memory")
    with pytest.raises(native.NativeBuildError):
        native.sanitize_mode()


def test_tsan_suppression_file_ships():
    from horovod_tpu.core import native

    assert os.path.exists(native.TSAN_SUPPRESSIONS)
    active = [ln.strip() for ln in open(native.TSAN_SUPPRESSIONS)
              if ln.strip() and not ln.strip().startswith("#")]
    assert active, "suppression file has no active entries"
    # Host-noise suppressions only: nothing may match engine frames.
    assert all("hvdcore" not in ln for ln in active), active


@pytest.mark.slow
@pytest.mark.skipif(not _slow_on,
                    reason="TSan smoke is the opt-in tier: "
                           "HVD_SLOW_TESTS=1 to run")
def test_tsan_native_engine_smoke():
    """HVD_SANITIZE=thread produces a working instrumented build, and a
    multi-threaded native-engine workout under it reports ZERO races
    (with the shipped suppression file quieting uninstrumented-host
    noise only)."""
    from horovod_tpu.core import native

    lib = native.build_library(mode="thread")
    runtime = native.sanitizer_runtime("thread")
    env = dict(os.environ)
    env["LD_PRELOAD"] = runtime
    env["HVD_SANITIZE"] = "thread"
    env["TSAN_OPTIONS"] = (f"suppressions={native.TSAN_SUPPRESSIONS} "
                           "exitcode=66 halt_on_error=0")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tests",
                                      "tsan_smoke_worker.py")],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert os.path.exists(lib)
    assert proc.returncode == 0, (proc.returncode, proc.stdout[-2000:],
                                  proc.stderr[-4000:])
    assert "TSAN_SMOKE_OK" in proc.stdout
    assert "WARNING: ThreadSanitizer" not in proc.stderr, \
        proc.stderr[-4000:]


@pytest.mark.slow
@pytest.mark.skipif(not _slow_on,
                    reason="ASan smoke is the opt-in tier: "
                           "HVD_SLOW_TESTS=1 to run")
def test_asan_native_engine_smoke():
    """HVD_SANITIZE=address produces a working instrumented build, and
    the same multi-threaded native-engine workout as the TSan smoke
    reports ZERO AddressSanitizer errors (PR 14 follow-up — the
    ASan-tier mirror). Leak detection stays OFF: the engine leaks
    by DOCTRINE (quiesce-then-leak, parked donations), and the
    uninstrumented CPython host would drown the report regardless —
    this smoke is about overflows/use-after-free in the C++ core."""
    from horovod_tpu.core import native

    lib = native.build_library(mode="address")
    runtime = native.sanitizer_runtime("address")
    env = dict(os.environ)
    env["LD_PRELOAD"] = runtime
    env["HVD_SANITIZE"] = "address"
    env["ASAN_OPTIONS"] = ("detect_leaks=0 abort_on_error=0 "
                           "exitcode=66 allocator_may_return_null=1")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tests",
                                      "tsan_smoke_worker.py")],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert os.path.exists(lib)
    assert proc.returncode == 0, (proc.returncode, proc.stdout[-2000:],
                                  proc.stderr[-4000:])
    assert "TSAN_SMOKE_OK" in proc.stdout  # same worker, same marker
    assert "ERROR: AddressSanitizer" not in proc.stderr, \
        proc.stderr[-4000:]
