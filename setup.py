"""Packaging with native-extension build (reference: setup.py — 770 lines
of MPI/CUDA/NCCL feature detection; here the native engine needs only a
C++17 toolchain, so the build reduces to one g++ invocation).

    pip install .           # builds libhvdcore.so into the wheel
    HVD_SKIP_NATIVE=1 pip install .   # python-engine-only install
"""

import os
import subprocess

from setuptools import Command, find_packages, setup
from setuptools.command.build_py import build_py


class BuildNative(Command):
    """Compile libhvdcore.so next to its source (the runtime also builds
    on demand, so failure here degrades to the python engine rather than
    failing the install — the reference instead hard-fails without MPI)."""

    description = "build the native engine"
    user_options = []

    def initialize_options(self):  # noqa: D102
        pass

    def finalize_options(self):  # noqa: D102
        pass

    def run(self):  # noqa: D102
        if os.environ.get("HVD_SKIP_NATIVE"):
            return
        src = os.path.join("horovod_tpu", "core", "native", "hvdcore.cc")
        out = os.path.join("horovod_tpu", "core", "native", "libhvdcore.so")
        cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
               "-Wall", src, "-o", out]
        try:
            subprocess.run(cmd, check=True)
        except (OSError, subprocess.CalledProcessError) as e:
            print(f"WARNING: native engine build failed ({e}); "
                  "the python engine will be used (HVD_ENGINE=python)")


class BuildPy(build_py):
    def run(self):
        self.run_command("build_native")
        super().run()


setup(
    name="horovod_tpu",
    version="0.1.0",
    description="TPU-native distributed training framework "
                "(Horovod-capability parity on JAX/XLA)",
    packages=find_packages(include=["horovod_tpu", "horovod_tpu.*"]),
    package_data={"horovod_tpu.core.native": ["*.so", "*.cc"]},
    python_requires=">=3.10",
    install_requires=["jax", "flax", "optax", "numpy", "scipy"],
    extras_require={
        "torch": ["torch"],
        "tensorflow": ["tensorflow"],
        "haiku": ["dm-haiku"],
    },
    cmdclass={"build_native": BuildNative, "build_py": BuildPy},
)
